"""Unified telemetry: metrics registry, Prometheus exposition, trace spans.

The reference has **no observability subsystem** (SURVEY.md §5.1 "Tracing
/ profiling — ABSENT", §5.5 "No Prometheus/OTel"), and this rebuild had
four mutually-incompatible private accounting schemes: the decode
engine's ``_completed`` tuples, the micro-batcher's ``_done`` list,
:class:`~unionml_tpu.diagnostics.StepTimer`, and free-form
``logger.info`` strings. This module is the single spine that replaces
them:

- :class:`MetricsRegistry` — a dependency-free, thread-safe registry of
  **Counter / Gauge / Histogram** families with label sets. Histograms
  use fixed log-spaced ms buckets (:data:`DEFAULT_MS_BUCKETS`) so
  percentile math is mergeable across threads and scrapers, plus a
  bounded raw-sample window so the existing ``stats()`` percentile
  summaries stay exact rather than bucket-approximated.
- ``registry.exposition()`` — Prometheus text exposition format 0.0.4,
  served at ``GET /metrics`` by both HTTP transports
  (:mod:`unionml_tpu.serving.http` and :mod:`unionml_tpu.serving.fastapi`).
- :class:`TraceRecorder` — per-request trace spans on the monotonic
  clock (``queue → prefill → decode-chunk[i] → harvest`` in the decode
  engine), keyed by a generated request id, exportable as Chrome
  trace-event JSON (loads in Perfetto / ``chrome://tracing``) and as
  structured JSON lines. Every request timeline carries a real **W3C
  trace context** (128-bit trace id, 64-bit span ids, parent links):
  the transports parse an inbound ``traceparent`` header
  (:func:`parse_traceparent`), open a :func:`trace_scope` around the
  predictor call, and the recorder picks the ambient context up in
  :meth:`~TraceRecorder.new_request` — so engine/batcher spans join
  the caller's distributed trace, and the OTLP exporter
  (:mod:`unionml_tpu.exporters`) can ship a connected span tree.

- :class:`FlightRecorder` — a bounded ring buffer of per-request
  lifecycle events (submit, prefill, decode chunks, sheds, recoveries)
  the engine and batcher record into; dumped at ``GET /debug/flight``
  and snapshotted into recovery trace spans for postmortems
  (docs/observability.md).
- :func:`percentile_summary` — the shared nearest-rank percentile
  formula every stats surface uses (moved here from
  ``serving._stats``, which re-exports it).
- :func:`publish_process_metrics` — the standard
  ``process_start_time_seconds`` and ``unionml_tpu_build_info`` gauges,
  published into every scraped registry.

Process-global defaults (:func:`get_registry`, :func:`get_tracer`,
:func:`get_flight_recorder`) make independently-constructed components
(an engine built outside the ``ServingApp``, a trainer loop in the same
process) land in the one scrape surface; pass explicit instances for
isolation. Everything here is stdlib-only and safe to import before
jax.
"""

from __future__ import annotations

import bisect
import itertools
import json
import math
import os
import re
import sys
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

__all__ = [
    "DEFAULT_MS_BUCKETS",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "TraceContext",
    "TraceRecorder",
    "current_trace_context",
    "format_traceparent",
    "get_flight_recorder",
    "get_registry",
    "get_tracer",
    "instance_label",
    "merge_expositions",
    "new_request_id",
    "new_span_id",
    "new_trace_id",
    "parse_traceparent",
    "SlidingSamples",
    "percentile_summary",
    "publish_process_metrics",
    "server_trace_context",
    "stitched_trace",
    "trace_scope",
    "wall_clock_offset_ms",
]


def percentile_summary(values: Sequence[float]) -> dict:
    """p50/p95/p99/mean/n of a non-empty sample.

    Percentiles use nearest-rank ``ceil(q * n) - 1`` (the formula the
    benchmarks, histogram summaries, StepTimer, and the program
    registry all share through this helper): for small windows
    ``int(q * n)`` indexes the sample MAXIMUM — one cold-compile outlier
    would be reported as the p95 and misdirect tail-latency attribution.
    ``n`` is the sample count, so a consumer can tell a p99 computed
    over 3 requests from one computed over 10k.

    (Moved here from ``unionml_tpu.serving._stats``, which re-exports
    it: non-serving modules — diagnostics, introspection — need it too,
    and telemetry is the layer they all already import.)
    """
    vals = sorted(values)
    n = len(vals)
    return {
        "p50": round(vals[n // 2], 1),
        "p95": round(vals[max(0, math.ceil(0.95 * n) - 1)], 1),
        "p99": round(vals[max(0, math.ceil(0.99 * n) - 1)], 1),
        "mean": round(sum(vals) / n, 1),
        "n": n,
    }


class SlidingSamples:
    """A bounded sliding window of float samples with nearest-rank
    percentile reads — the live-quantile primitive behind adaptive
    decisions (the fleet router's hedge delay tracks the request p95
    through one of these; a Histogram can't serve that read because
    its buckets quantize to the grid and never age out old regimes).

    Thread-safe; O(1) add, O(n log n) percentile (n <= maxlen, read on
    decision paths that already cost a dispatch)."""

    def __init__(self, maxlen: int = 512):
        if maxlen < 1:
            raise ValueError(f"maxlen must be >= 1, got {maxlen}")
        self._samples: "deque[float]" = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def add(self, value: float) -> None:
        with self._lock:
            self._samples.append(float(value))

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    def percentile(self, q: float, default: float = 0.0) -> float:
        """Nearest-rank q-quantile (``ceil(q*n) - 1``, the repo-wide
        formula — see :func:`percentile_summary`); ``default`` when no
        samples have landed yet."""
        if not 0.0 < q <= 1.0:
            raise ValueError(f"q must be in (0, 1], got {q}")
        with self._lock:
            if not self._samples:
                return default
            vals = sorted(self._samples)
        return vals[max(0, math.ceil(q * len(vals)) - 1)]

    def mean(self, default: float = 0.0) -> float:
        """Window mean (the rolling-average read behind the router's
        weighted least-request latency term); ``default`` when empty."""
        with self._lock:
            if not self._samples:
                return default
            return sum(self._samples) / len(self._samples)


# log-spaced ms buckets (1 / 2.5 / 5 per decade, 100 µs .. 1 min): wide
# enough for a fused decode step (~2 ms) and a cold XLA compile (~20 s)
# in the same family, few enough that per-observation cost is one bisect
DEFAULT_MS_BUCKETS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

_instance_counters: Dict[str, "itertools.count"] = {}
_instance_lock = threading.Lock()


def new_request_id() -> str:
    """A 16-hex-char request id (the ``X-Request-ID`` / trace key)."""
    return uuid.uuid4().hex[:16]


def instance_label(prefix: str) -> str:
    """Process-unique label value for one component instance
    (``engine-0``, ``batcher-3``, ...): keeps every instance's series
    separate in the shared registry without unbounded cardinality."""
    with _instance_lock:
        counter = _instance_counters.setdefault(prefix, itertools.count())
        return f"{prefix}-{next(counter)}"


def _escape_label_value(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(value: float) -> str:
    if value != value:  # NaN
        return "NaN"
    if value in (float("inf"), float("-inf")):
        return "+Inf" if value > 0 else "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _label_pairs(labelnames: Sequence[str], values: Sequence[str]) -> str:
    if not labelnames:
        return ""
    inner = ",".join(
        f'{n}="{_escape_label_value(v)}"' for n, v in zip(labelnames, values)
    )
    return "{" + inner + "}"


class _Child:
    """One labeled series of a family; shares the family lock."""

    def __init__(self, family: "_Family", values: Tuple[str, ...]):
        self._family = family
        self._lock = family._lock
        self._values = values


class Counter(_Child):
    """Monotonic counter. ``reset()`` exists for windowed ``stats()``
    views (benchmarks zero the window between scenarios); Prometheus
    scrapers tolerate resets as counter restarts."""

    def __init__(self, family: "_Family", values: Tuple[str, ...]):
        super().__init__(family, values)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up (inc({amount}))")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0


class Gauge(_Child):
    """Settable value; ``set_function`` registers a callable sampled at
    read time (for values owned elsewhere, e.g. queue depth)."""

    def __init__(self, family: "_Family", values: Tuple[str, ...]):
        super().__init__(family, values)
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        with self._lock:
            self._fn = None
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    def set_function(self, fn: Callable[[], float]) -> None:
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:  # sampled outside the lock: user callables may be slow
            return float(fn())
        except Exception:
            return 0.0

    def reset(self) -> None:
        with self._lock:
            self._fn = None
            self._value = 0.0


class Histogram(_Child):
    """Bucketed distribution + a bounded raw-sample window.

    The buckets feed the mergeable Prometheus exposition; the window
    (capped like the accounting lists it replaces: 10k samples, trimmed
    to the newest 5k) feeds :meth:`summary`'s exact percentiles so
    ``stats()`` output keeps its historical meaning.

    Exemplars (Dapper lineage): an ``observe`` call may attach a
    request id, kept in a bounded ring of ``(value, exemplar)`` pairs.
    :meth:`exemplars` returns the largest recent values with their
    ids, which is how ``GET /debug/tail`` links a p99 spike back to
    the exact request (``/debug/trace?rid=``) that caused it. The
    ring is recency-bounded, not value-sorted, so old outliers age
    out and the view stays "slowest *recent* requests".
    """

    WINDOW_CAP = 10_000
    EXEMPLAR_CAP = 64

    def __init__(self, family: "_Family", values: Tuple[str, ...]):
        super().__init__(family, values)
        self._bounds = family._buckets
        self._counts = [0] * (len(self._bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._window: List[float] = []
        self._exemplars: List[Tuple[float, str]] = []

    def observe(self, value: float, exemplar: Optional[str] = None) -> None:
        value = float(value)
        idx = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            self._window.append(value)
            if len(self._window) > self.WINDOW_CAP:
                del self._window[: self.WINDOW_CAP // 2]
            if exemplar is not None:
                self._exemplars.append((value, str(exemplar)))
                if len(self._exemplars) > self.EXEMPLAR_CAP:
                    del self._exemplars[: self.EXEMPLAR_CAP // 2]

    def exemplars(self, n: int = 5) -> List[Tuple[float, str]]:
        """The ``n`` largest recent ``(value, exemplar)`` pairs,
        slowest first — the per-series tail view behind
        ``GET /debug/tail``."""
        with self._lock:
            pairs = list(self._exemplars)
        pairs.sort(key=lambda p: p[0], reverse=True)
        return pairs[: max(0, int(n))]

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def buckets(self) -> List[Tuple[float, int]]:
        """Cumulative ``(upper_bound, count)`` pairs, ``+Inf`` last."""
        with self._lock:
            counts = list(self._counts)
        out, running = [], 0
        for bound, n in zip(self._bounds + (float("inf"),), counts):
            running += n
            out.append((bound, running))
        return out

    def summary(self) -> dict:
        """Exact ``percentile_summary`` of the retained window (the
        ``stats()`` view); ``{}`` when nothing was observed."""
        with self._lock:
            window = list(self._window)
        if not window:
            return {}
        return percentile_summary(window)

    def samples(self) -> List[float]:
        """The retained raw-sample window (oldest first) — cross-series
        percentile reads (e.g. engine ITL merged over its priority
        children) recompute exact percentiles from these."""
        with self._lock:
            return list(self._window)

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self._bounds) + 1)
            self._sum = 0.0
            self._count = 0
            self._window.clear()
            self._exemplars.clear()


class _Family:
    """A named metric with a fixed label schema and per-labelset children."""

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Tuple[str, ...],
        kind: str,
        child_cls: type,
        buckets: Optional[Tuple[float, ...]] = None,
    ):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for label in labelnames:
            if not _LABEL_RE.match(label) or label.startswith("__"):
                raise ValueError(f"invalid label name {label!r}")
        self.name = name
        self.help = help
        self.labelnames = labelnames
        self.kind = kind
        self._child_cls = child_cls
        self._buckets = buckets
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Any] = {}
        self._default: Optional[Any] = None
        if not labelnames:
            self._default = self.labels()

    def labels(self, *values: str, **kwargs: str):
        if kwargs:
            if values:
                raise ValueError("pass label values positionally OR by name")
            try:
                values = tuple(str(kwargs[n]) for n in self.labelnames)
            except KeyError as exc:
                raise ValueError(
                    f"{self.name} needs labels {self.labelnames}, got "
                    f"{sorted(kwargs)}"
                ) from exc
            if len(kwargs) != len(self.labelnames):
                raise ValueError(
                    f"{self.name} needs labels {self.labelnames}, got "
                    f"{sorted(kwargs)}"
                )
        else:
            values = tuple(str(v) for v in values)
            if len(values) != len(self.labelnames):
                raise ValueError(
                    f"{self.name} takes {len(self.labelnames)} label "
                    f"value(s) {self.labelnames}, got {len(values)}"
                )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._child_cls(self, values)
                self._children[values] = child
        return child

    # unlabeled families proxy straight to their single child, so
    # `registry.counter("x", "...").inc()` needs no `.labels()` hop
    def __getattr__(self, attr: str):
        if attr.startswith("_"):  # dunder/private lookups must not recurse
            raise AttributeError(attr)
        default = self.__dict__.get("_default")
        if default is not None:
            return getattr(default, attr)
        raise AttributeError(
            f"{self.name} has labels {self.labelnames} — call .labels(...) "
            f"before .{attr}"
        )

    def children(self) -> List[Tuple[Tuple[str, ...], Any]]:
        with self._lock:
            return list(self._children.items())

    def reset(self) -> None:
        for _, child in self.children():
            child.reset()

    def render(self) -> Iterator[str]:
        yield f"# HELP {self.name} {_escape_help(self.help)}"
        yield f"# TYPE {self.name} {self.kind}"
        for values, child in sorted(self.children()):
            labels = _label_pairs(self.labelnames, values)
            if self.kind == "histogram":
                for bound, cum in child.buckets():
                    le = "+Inf" if math.isinf(bound) else _fmt(bound)
                    pairs = _label_pairs(
                        self.labelnames + ("le",), values + (le,)
                    )
                    yield f"{self.name}_bucket{pairs} {cum}"
                yield f"{self.name}_sum{labels} {_fmt(child.sum)}"
                yield f"{self.name}_count{labels} {child.count}"
            else:
                yield f"{self.name}{labels} {_fmt(child.value)}"


class MetricsRegistry:
    """Thread-safe get-or-create registry of metric families.

    Re-requesting a family with the same name returns the existing one
    (components built at different times share series); a name re-used
    with a different type or label schema raises — silent merging would
    corrupt the exposition.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _get_or_create(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str],
        kind: str,
        child_cls: type,
        buckets: Optional[Tuple[float, ...]] = None,
    ) -> _Family:
        labelnames = tuple(labelnames)
        with self._lock:
            family = self._families.get(name)
            if family is not None:
                if family.kind != kind or family.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name} already registered as {family.kind}"
                        f"{family.labelnames}, requested {kind}{labelnames}"
                    )
                return family
            family = _Family(name, help, labelnames, kind, child_cls, buckets)
            self._families[name] = family
            return family

    def counter(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> _Family:
        return self._get_or_create(name, help, labelnames, "counter", Counter)

    def gauge(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> _Family:
        return self._get_or_create(name, help, labelnames, "gauge", Gauge)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
    ) -> _Family:
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        family = self._get_or_create(
            name, help, labelnames, "histogram", Histogram, bounds
        )
        if family._buckets != bounds:
            raise ValueError(
                f"metric {name} already registered with buckets "
                f"{family._buckets}, requested {bounds}"
            )
        return family

    def collect(self) -> List[_Family]:
        with self._lock:
            return list(self._families.values())

    def exposition(self) -> str:
        """Prometheus text exposition format 0.0.4 (the ``GET /metrics``
        body; serve with content type :data:`EXPOSITION_CONTENT_TYPE`)."""
        lines: List[str] = []
        for family in sorted(self.collect(), key=lambda f: f.name):
            lines.extend(family.render())
        return "\n".join(lines) + "\n" if lines else ""

    def snapshot(self) -> dict:
        """``{name: {labelset_repr: value_or_histogram_dict}}`` — the
        debug/test view (scrapers should use :meth:`exposition`)."""
        out: dict = {}
        for family in self.collect():
            series = {}
            for values, child in family.children():
                key = ",".join(
                    f"{n}={v}" for n, v in zip(family.labelnames, values)
                )
                if family.kind == "histogram":
                    series[key] = {
                        "count": child.count,
                        "sum": child.sum,
                        "buckets": child.buckets(),
                    }
                else:
                    series[key] = child.value
            out[family.name] = series
        return out

    def reset(self) -> None:
        for family in self.collect():
            family.reset()


EXPOSITION_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


# --------------------------------------------------------------------- #
# metrics federation: exposition parse + merge
# --------------------------------------------------------------------- #

# one exposition sample line: `name{labels} value [timestamp]` or
# `name value` (the subset both our exposition and Prometheus clients
# emit; unparseable lines are dropped rather than corrupting the merge)
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(.+)$"
)
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) ?(.*)$")
_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (\w+)$")
_EXPOSITION_SUFFIXES = ("_bucket", "_sum", "_count")


def _parse_exposition(text: str) -> "List[dict]":
    """Ordered families ``{name, help, type, samples: [(name, labels,
    value)]}`` from one exposition body. Samples are grouped under the
    nearest preceding ``# TYPE``/``# HELP`` family when their name
    matches it (histogram ``_bucket``/``_sum``/``_count`` suffixes
    included); headerless samples open an implicit family."""
    families: List[dict] = []
    by_name: Dict[str, dict] = {}

    def family(name: str) -> dict:
        fam = by_name.get(name)
        if fam is None:
            fam = {"name": name, "help": None, "type": None, "samples": []}
            by_name[name] = fam
            families.append(fam)
        return fam

    current: Optional[dict] = None
    for line in text.splitlines():
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            m = _HELP_RE.match(line)
            if m is not None:
                current = family(m.group(1))
                if current["help"] is None:
                    current["help"] = m.group(2)
                continue
            m = _TYPE_RE.match(line)
            if m is not None:
                current = family(m.group(1))
                if current["type"] is None:
                    current["type"] = m.group(2)
                continue
            continue  # other comments dropped
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue  # unparseable line: drop, never corrupt the merge
        name, labels, value = m.groups()
        owner = None
        if current is not None:
            base = current["name"]
            if name == base or (
                name.startswith(base)
                and name[len(base):] in _EXPOSITION_SUFFIXES
            ):
                owner = current
        if owner is None:
            owner = family(name)
        owner["samples"].append((name, labels or "", value))
    return families


def _label_sample(
    sample: "Tuple[str, str, str]", label: str, value: str
) -> str:
    """One sample line with ``label="value"`` injected as the first
    label — unless the sample already carries ``label`` (a federated
    replica that is itself a router keeps its own, more specific,
    replica names)."""
    name, labels, val = sample
    pair = f'{label}="{_escape_label_value(value)}"'
    if labels:
        inner = labels[1:-1]
        if re.search(rf'(^|,){label}="', inner):
            return f"{name}{labels} {val}"
        return f"{name}{{{pair},{inner}}} {val}"
    return f"{name}{{{pair}}} {val}"


def merge_expositions(
    local: str,
    replicas: Dict[str, str],
    label: str = "replica",
) -> str:
    """One fleet-wide Prometheus exposition: ``local`` (the router's
    own registry, untouched) merged with each replica's exposition
    under an injected ``replica="<name>"`` label — the federation body
    the router app serves at ``GET /metrics`` so an operator scrapes
    ONE target for the whole fleet (docs/observability.md "Fleet
    observability").

    Families shared across sources render once (``# HELP``/``# TYPE``
    from the first source that declared them — the text format
    requires a family's samples grouped under one header); the
    ``replica`` label's value set is the router's membership, so its
    cardinality is bounded by the fleet size, never by traffic.
    Replica bodies that fail to parse contribute nothing — a corrupt
    scrape degrades to absent series, never to a broken exposition."""
    merged = _parse_exposition(local)
    by_name = {fam["name"]: fam for fam in merged}
    for replica_name in sorted(replicas):
        text = replicas[replica_name]
        if not text:
            continue
        for fam in _parse_exposition(text):
            target = by_name.get(fam["name"])
            if target is None:
                target = {
                    "name": fam["name"], "help": fam["help"],
                    "type": fam["type"], "samples": [],
                }
                by_name[fam["name"]] = target
                merged.append(target)
            elif target["help"] is None:
                target["help"] = fam["help"]
            if target["type"] is None:
                target["type"] = fam["type"]
            target["samples"].extend(
                (None, None, _label_sample(s, label, replica_name))
                for s in fam["samples"]
            )
    lines: List[str] = []
    for fam in sorted(merged, key=lambda f: f["name"]):
        if not fam["samples"]:
            continue
        if fam["help"] is not None:
            lines.append(f"# HELP {fam['name']} {fam['help']}")
        if fam["type"] is not None:
            lines.append(f"# TYPE {fam['name']} {fam['type']}")
        for sample in fam["samples"]:
            if sample[0] is None:
                lines.append(sample[2])  # pre-rendered replica line
            else:
                name, labels, value = sample
                lines.append(f"{name}{labels} {value}")
    return "\n".join(lines) + "\n" if lines else ""


# --------------------------------------------------------------------- #
# W3C trace context (https://www.w3.org/TR/trace-context/)
# --------------------------------------------------------------------- #

# version 00: `00-<32 hex trace-id>-<16 hex parent-id>-<2 hex flags>`;
# all-zero trace/span ids are invalid per spec and treated as absent
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


@dataclass(frozen=True)
class TraceContext:
    """One W3C trace-context position: the trace a request belongs to
    (``trace_id``, 32 hex chars) and the span new children should
    parent to (``span_id``, 16 hex chars). ``sampled`` mirrors the
    ``traceparent`` sampled flag (recording here never depends on it;
    it is echoed so downstream samplers see the caller's decision)."""

    trace_id: str
    span_id: str
    sampled: bool = True


def new_trace_id() -> str:
    """A 32-hex-char (128-bit) W3C trace id (never all-zero: uuid4's
    version bits are fixed)."""
    return uuid.uuid4().hex


def new_span_id() -> str:
    """A 16-hex-char (64-bit) W3C span id (never all-zero: the uuid4
    version nibble lands inside the first 16 chars)."""
    return uuid.uuid4().hex[:16]


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """Parse a ``traceparent`` header into a :class:`TraceContext`.

    Returns ``None`` for an absent OR malformed header — the transport
    contract is to mint a fresh root in that case, never to 5xx a
    request over its tracing metadata (a broken upstream proxy must not
    take serving down). Rejected per spec: bad shape/hex, version
    ``ff``, all-zero trace or span id. Future versions (``01``+) parse
    leniently as version-00, as the spec requires."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff":
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id, span_id, sampled=bool(int(flags, 16) & 1))


def format_traceparent(ctx: TraceContext) -> str:
    """Render a :class:`TraceContext` as a version-00 ``traceparent``
    header value (what transports echo on responses)."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


def server_trace_context(raw_header: Optional[str]) -> TraceContext:
    """The context a transport should echo for routes that do not open
    a recorded server timeline (health, metrics, debug): the caller's
    trace id when a valid ``traceparent`` arrived (else a minted root),
    with a fresh span id — enough for the caller to correlate the
    response with its trace."""
    inbound = parse_traceparent(raw_header)
    return TraceContext(
        trace_id=inbound.trace_id if inbound else new_trace_id(),
        span_id=new_span_id(),
        sampled=inbound.sampled if inbound else True,
    )


_trace_tls = threading.local()


@contextmanager
def trace_scope(ctx: Optional[TraceContext]) -> Iterator[None]:
    """Expose ``ctx`` to :meth:`TraceRecorder.new_request` calls made on
    this thread (``None`` is a no-op scope). The transports parse the
    inbound ``traceparent``, open this scope around the predictor call,
    and the engine/batcher timelines created inside it join the
    caller's trace — deadline-scope-style thread-local plumbing, so no
    predictor wrapper has to thread a context kwarg through."""
    prev = getattr(_trace_tls, "ctx", None)
    _trace_tls.ctx = ctx
    try:
        yield
    finally:
        _trace_tls.ctx = prev


def current_trace_context() -> Optional[TraceContext]:
    """The innermost :func:`trace_scope` context on this thread."""
    return getattr(_trace_tls, "ctx", None)


# --------------------------------------------------------------------- #
# trace spans
# --------------------------------------------------------------------- #


class TraceRecorder:
    """Per-request trace spans on the monotonic clock.

    ``new_request()`` issues a generated request id; spans attach to it
    via :meth:`record_span` (explicit start/end, for producer/consumer
    pipelines where one thread dispatches and another harvests) or the
    :meth:`span` context manager. ``finish_request`` moves the request
    to a bounded completed ring (newest ``max_requests`` kept).

    Distributed context: every request timeline carries a W3C trace id,
    a root span id, and (when created inside a :func:`trace_scope`, or
    with an explicit ``trace_ctx``) a parent span id linking it to the
    caller's span — so the exported spans form a connected tree across
    services. Each recorded span gets its own span id, parented to the
    request's root span. A request whose span cap was hit is marked
    ``truncated`` in its meta and counted in
    ``unionml_trace_spans_dropped_total``, so a postmortem reader knows
    the trace is partial rather than silently short.

    Exports:

    - :meth:`export_chrome` — Chrome trace-event JSON (``ph: "X"``
      complete events, µs timestamps), loads in Perfetto and
      ``chrome://tracing``; one virtual thread row per request.
    - :meth:`export_jsonl` — one JSON object per span per line
      (including the trace/span/parent ids), for log shippers.
    - listeners (:meth:`add_listener`) see each finished request once —
      the push seam the OTLP exporter
      (:mod:`unionml_tpu.exporters`) subscribes to.
    """

    MAX_SPANS_PER_REQUEST = 4096
    MAX_EVENTS_PER_REQUEST = 512

    def __init__(
        self,
        max_requests: int = 1024,
        registry: Optional["MetricsRegistry"] = None,
    ):
        self.max_requests = max_requests
        self._lock = threading.Lock()
        self._live: Dict[str, List[dict]] = {}
        self._meta: Dict[str, dict] = {}
        self._done: List[Tuple[str, dict, List[dict]]] = []
        self._tids: Dict[str, int] = {}
        self._next_tid = itertools.count(1)
        # resolved lazily: the process-global recorder is constructed
        # alongside the process-global registry at module init
        self._registry = registry
        self._m_dropped: Optional[Counter] = None
        self._listeners: List[Callable[[str, dict, List[dict]], None]] = []

    def add_listener(
        self, fn: Callable[[str, dict, List[dict]], None]
    ) -> None:
        """Subscribe ``fn(rid, meta, spans)`` to every finished request
        (called outside the recorder lock, exceptions swallowed) — the
        push-export seam."""
        with self._lock:
            self._listeners.append(fn)

    def remove_listener(
        self, fn: Callable[[str, dict, List[dict]], None]
    ) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def _count_dropped(self, n: int = 1) -> None:
        if self._m_dropped is None:
            reg = self._registry if self._registry is not None else get_registry()
            self._m_dropped = reg.counter(
                "unionml_trace_spans_dropped_total",
                "Spans dropped past MAX_SPANS_PER_REQUEST; the affected "
                "request's meta carries truncated=true.",
            )
        self._m_dropped.inc(n)

    def new_request(
        self,
        kind: str = "request",
        trace_ctx: Optional[TraceContext] = None,
        rid: Optional[str] = None,
        **meta: Any,
    ) -> str:
        """Open a request timeline. ``trace_ctx`` (explicit, or the
        ambient :func:`trace_scope` one on this thread) is the PARENT
        context: the timeline joins its trace and its root span parents
        to ``trace_ctx.span_id``; with neither, a fresh root trace is
        minted. ``rid`` keys the timeline under a caller-chosen request
        id (the transports pass their ``X-Request-ID`` so
        ``/debug/trace?rid=`` answers with the id the client actually
        holds); a colliding or absent ``rid`` falls back to a generated
        one — the RETURNED id is authoritative."""
        parent = trace_ctx if trace_ctx is not None else current_trace_context()
        with self._lock:
            if rid is None or rid in self._live or rid in self._tids:
                rid = new_request_id()
            self._live[rid] = []
            self._meta[rid] = {
                "kind": kind,
                "trace_id": parent.trace_id if parent else new_trace_id(),
                "span_id": new_span_id(),
                "parent_span_id": parent.span_id if parent else None,
                # the caller's sampling decision rides along so the
                # response echo carries it back (-00 stays -00)
                "sampled": parent.sampled if parent else True,
                "start_s": time.perf_counter(),
                **meta,
            }
            self._tids[rid] = next(self._next_tid)
        return rid

    def trace_context(self, rid: str) -> Optional[TraceContext]:
        """The (trace id, root span id) position of ``rid`` — what a
        child scope or a response ``traceparent`` echo should carry.
        ``None`` for unknown rids."""
        with self._lock:
            meta = self._meta.get(rid)
            if meta is None:
                for done_rid, done_meta, _ in reversed(self._done):
                    if done_rid == rid:
                        meta = done_meta
                        break
            if meta is None or "trace_id" not in meta:
                return None
            return TraceContext(
                meta["trace_id"], meta["span_id"],
                sampled=meta.get("sampled", True),
            )

    def record_span(
        self,
        rid: str,
        name: str,
        start_s: float,
        end_s: float,
        span_id: Optional[str] = None,
        parent_span_id: Optional[str] = None,
        **args: Any,
    ) -> None:
        """Attach one completed span (``time.perf_counter()`` seconds).
        Unknown/finished rids are ignored — a late harvest for an
        already-exported request must not KeyError the engine. A live
        request past the span cap drops the span, counts it, and flags
        the request ``truncated``.

        ``span_id`` lets a caller PRE-MINT the id (the fleet router
        mints each dispatch attempt's span id before dispatching, so
        the attempt's child context can propagate to the replica while
        the span is still open); ``parent_span_id`` overrides the
        default parent (the request's root span) for nested span
        trees."""
        span = {
            "name": name,
            "start_s": float(start_s),
            "end_s": float(end_s),
            "span_id": span_id if span_id is not None else new_span_id(),
        }
        if parent_span_id is not None:
            span["parent_span_id"] = parent_span_id
        if args:
            span["args"] = args
        with self._lock:
            spans = self._live.get(rid)
            if spans is None:
                return
            if len(spans) >= self.MAX_SPANS_PER_REQUEST:
                meta = self._meta.get(rid)
                if meta is not None:
                    meta["truncated"] = True
                dropped = True
            else:
                spans.append(span)
                dropped = False
        if dropped:
            self._count_dropped()

    def span(self, rid: str, name: str, **args: Any):
        """Context manager measuring one span around its body."""
        return _SpanContext(self, rid, name, args)

    def record_event(
        self, rid: str, name: str, t_s: Optional[float] = None, **args: Any
    ) -> None:
        """Attach one INSTANT event to a live request timeline (the
        OTLP span-event mapping: exported as events on the request's
        synthesized root span, as ``ph: "i"`` instants in the Chrome
        export, and as ``"event": true`` lines in jsonl). The fleet
        router's lifecycle (eject/probe/rejoin) and the autoscaler's
        scale decisions ride the fleet timeline this way, so a latency
        spike is explainable from the trace alone. Unknown rids are
        ignored; a request past the event cap drops the event, counts
        it, and flags the request ``truncated``."""
        event = {
            "name": name,
            "t_s": float(t_s) if t_s is not None else time.perf_counter(),
        }
        if args:
            event["args"] = args
        with self._lock:
            meta = self._meta.get(rid)
            if meta is None or rid not in self._live:
                return
            events = meta.setdefault("events", [])
            if len(events) >= self.MAX_EVENTS_PER_REQUEST:
                meta["truncated"] = True
                dropped = True
            else:
                events.append(event)
                dropped = False
        if dropped:
            self._count_dropped()

    def find_trace_id(self, rid: str) -> Optional[str]:
        """The W3C trace id of a locally-known request id (live or
        completed) — how ``/debug/trace?rid=`` resolves the id a
        client holds into the trace to stitch. ``None`` when
        unknown."""
        with self._lock:
            meta = self._meta.get(rid)
            if meta is None:
                for done_rid, done_meta, _ in reversed(self._done):
                    if done_rid == rid:
                        meta = done_meta
                        break
            if meta is None:
                return None
            return meta.get("trace_id")

    def requests_for_trace(
        self, trace_id: str
    ) -> List[Tuple[str, dict, List[dict]]]:
        """Every retained request (completed AND live) whose timeline
        belongs to ``trace_id`` — the local half of cross-hop trace
        stitching: one transport hop's server timeline, the router's
        routing timeline, and any in-process engine timelines of the
        same trace come back together."""
        return [
            (rid, meta, spans)
            for rid, meta, spans in self._all_requests()
            if meta.get("trace_id") == trace_id
        ]

    def finish_request(self, rid: str) -> None:
        with self._lock:
            spans = self._live.pop(rid, None)
            meta = self._meta.pop(rid, {"kind": "request"})
            if spans is None:
                return
            meta.setdefault("end_s", time.perf_counter())
            self._done.append((rid, meta, spans))
            if len(self._done) > self.max_requests:
                dropped = self._done[: -self.max_requests]
                del self._done[: -self.max_requests]
                for old_rid, _, _ in dropped:
                    self._tids.pop(old_rid, None)
            listeners = list(self._listeners)
        for fn in listeners:  # outside the lock: listeners may be slow
            try:
                fn(rid, meta, list(spans))
            except Exception:
                pass  # an exporter bug must never fail the request path

    def _all_requests(self) -> List[Tuple[str, dict, List[dict]]]:
        with self._lock:
            out = list(self._done)
            out.extend(
                (rid, self._meta.get(rid, {}), list(spans))
                for rid, spans in self._live.items()
            )
            return out

    def export_chrome(self) -> dict:
        """``{"traceEvents": [...], "displayTimeUnit": "ms"}`` — drop
        the JSON in Perfetto / ``chrome://tracing``. Timestamps are µs
        on the process-local monotonic clock (offsets are meaningful,
        absolute values are not)."""
        events: List[dict] = []
        with self._lock:
            tids = dict(self._tids)
        for rid, meta, spans in self._all_requests():
            tid = tids.get(rid, 0)
            for span in spans:
                event = {
                    "name": span["name"],
                    "cat": meta.get("kind", "request"),
                    "ph": "X",
                    "ts": round(span["start_s"] * 1e6, 3),
                    "dur": round((span["end_s"] - span["start_s"]) * 1e6, 3),
                    "pid": 0,
                    "tid": tid,
                    "args": {"request_id": rid, **span.get("args", {})},
                }
                events.append(event)
            for instant in meta.get("events", ()):
                events.append({
                    "name": instant["name"],
                    "cat": meta.get("kind", "request"),
                    "ph": "i",
                    "s": "t",
                    "ts": round(instant["t_s"] * 1e6, 3),
                    "pid": 0,
                    "tid": tid,
                    "args": {"request_id": rid, **instant.get("args", {})},
                })
            events.append({
                "name": "thread_name",
                "ph": "M",
                "pid": 0,
                "tid": tid,
                "args": {"name": f"{meta.get('kind', 'request')} {rid}"},
            })
        events.sort(key=lambda e: e.get("ts", 0.0))
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_jsonl(self) -> str:
        """One span per line: ``{"request_id", "name", "start_ms",
        "duration_ms", "trace_id", "span_id", "parent_span_id", ...}``
        (monotonic-clock ms). The W3C ids let a log pipeline join these
        lines with upstream services' spans: a request's lines share
        ``parent_span_id`` — its root span id, whose own parent (the
        upstream caller's span, when one was propagated) rides along as
        ``request_parent_span_id`` — so the chain
        upstream → request root → span is reconstructible from the
        lines alone. (The root span itself has no line; its timing is
        the min/max of its children, exactly how the OTLP exporter
        synthesizes it.)"""
        lines = []
        for rid, meta, spans in self._all_requests():
            for span in spans:
                record = {
                    "request_id": rid,
                    "kind": meta.get("kind", "request"),
                    "name": span["name"],
                    "start_ms": round(span["start_s"] * 1e3, 3),
                    "duration_ms": round(
                        (span["end_s"] - span["start_s"]) * 1e3, 3
                    ),
                }
                if "trace_id" in meta:
                    record["trace_id"] = meta["trace_id"]
                    record["span_id"] = span.get("span_id")
                    record["parent_span_id"] = (
                        span.get("parent_span_id") or meta["span_id"]
                    )
                    if meta.get("parent_span_id"):
                        record["request_parent_span_id"] = (
                            meta["parent_span_id"]
                        )
                if meta.get("truncated"):
                    record["truncated"] = True
                record.update(span.get("args", {}))
                lines.append(json.dumps(record))
            for instant in meta.get("events", ()):
                record = {
                    "request_id": rid,
                    "kind": meta.get("kind", "request"),
                    "event": True,
                    "name": instant["name"],
                    "t_ms": round(instant["t_s"] * 1e3, 3),
                }
                if "trace_id" in meta:
                    record["trace_id"] = meta["trace_id"]
                    record["span_id"] = meta["span_id"]
                record.update(instant.get("args", {}))
                lines.append(json.dumps(record))
        return "\n".join(lines) + "\n" if lines else ""

    def reset(self) -> None:
        with self._lock:
            self._live.clear()
            self._meta.clear()
            self._done.clear()
            self._tids.clear()


class _SpanContext:
    def __init__(self, recorder: TraceRecorder, rid: str, name: str, args: dict):
        self._recorder = recorder
        self._rid = rid
        self._name = name
        self._args = args
        self._t0 = 0.0

    def __enter__(self) -> "_SpanContext":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._recorder.record_span(
            self._rid, self._name, self._t0, time.perf_counter(), **self._args
        )


def stitched_trace(
    trace_id: Optional[str],
    requests: Sequence[Tuple[str, dict, List[dict]]],
) -> dict:
    """Flatten recorder requests of ONE trace into the stitched
    end-to-end timeline document ``GET /debug/trace?rid=`` serves:

    ``{"trace_id", "request_ids", "spans": [...], "events": [...]}``

    Each request contributes a synthesized root span (named by its
    kind, spanning its children — the same root the OTLP exporter
    ships, so the JSON view and the collector agree) plus its recorded
    spans, every span carrying real W3C ``span_id``/``parent_span_id``
    links: the parent chain caller → transport → router attempt →
    replica server span is reconstructible from one document.

    Timestamps are ``start_unix_ms`` — the monotonic readings anchored
    to THIS process's wall clock at export time — so spans fetched
    from different replicas sort into one timeline at NTP accuracy
    (within one process, offsets keep monotonic-clock exactness).
    """
    # wall anchor (lint: wall clock is fine here — this converts to an
    # epoch timestamp for cross-process alignment, not a duration)
    wall_offset_s = time.time() - time.perf_counter()

    def unix_ms(perf_s: float) -> float:
        return round((perf_s + wall_offset_s) * 1e3, 3)

    spans: List[dict] = []
    events: List[dict] = []
    request_ids: List[str] = []
    for rid, meta, req_spans in requests:
        request_ids.append(rid)
        root_id = meta.get("span_id") or new_span_id()
        start_s = meta.get("start_s")
        end_s = meta.get("end_s")
        if req_spans:
            bounds = [s["start_s"] for s in req_spans]
            start_s = min(bounds + ([start_s] if start_s is not None else []))
            ends = [s["end_s"] for s in req_spans]
            end_s = max(ends + ([end_s] if end_s is not None else []))
        if start_s is None:
            continue  # nothing measurable yet (empty live request)
        if end_s is None:
            end_s = start_s  # live request: zero-length root so far
        root: dict = {
            "request_id": rid,
            "kind": meta.get("kind", "request"),
            "name": str(meta.get("kind", "request")),
            "span_id": root_id,
            "parent_span_id": meta.get("parent_span_id"),
            "root": True,
            "start_unix_ms": unix_ms(start_s),
            "duration_ms": round((end_s - start_s) * 1e3, 3),
        }
        if meta.get("truncated"):
            root["truncated"] = True
        spans.append(root)
        for span in req_spans:
            spans.append({
                "request_id": rid,
                "kind": meta.get("kind", "request"),
                "name": span["name"],
                "span_id": span.get("span_id"),
                "parent_span_id": span.get("parent_span_id") or root_id,
                "start_unix_ms": unix_ms(span["start_s"]),
                "duration_ms": round(
                    (span["end_s"] - span["start_s"]) * 1e3, 3
                ),
                **span.get("args", {}),
            })
        for instant in meta.get("events", ()):
            events.append({
                "request_id": rid,
                "name": instant["name"],
                "span_id": root_id,
                "t_unix_ms": unix_ms(instant["t_s"]),
                **instant.get("args", {}),
            })
    spans.sort(key=lambda s: s["start_unix_ms"])
    events.sort(key=lambda e: e["t_unix_ms"])
    return {
        "trace_id": trace_id,
        "request_ids": request_ids,
        "spans": spans,
        "events": events,
    }


def wall_clock_offset_ms() -> float:
    """Milliseconds to ADD to a monotonic-clock ``t_ms`` reading to
    get epoch milliseconds — the per-host anchor the fleet flight
    merge rebases replica rings with: each host's monotonic epoch is
    its boot time, so raw ``t_ms`` values are incomparable across
    machines (a host up 30 days sorts after a fresh one regardless of
    real time). Wall-anchored times compare at NTP accuracy; within
    one host, offsets between events stay monotonic-exact. (Lint: the
    wall clock is fine here — this is epoch anchoring, not a
    duration.)"""
    return (time.time() - time.monotonic()) * 1e3


# --------------------------------------------------------------------- #
# flight recorder
# --------------------------------------------------------------------- #


class FlightRecorder:
    """Bounded ring buffer of per-request lifecycle events — the
    postmortem record behind ``GET /debug/flight``.

    The engine and batcher :meth:`record` structured events (submit,
    prefill, decode chunks, sheds with their cause, recoveries) as they
    happen; appends are O(1) on a preallocated deque under one lock, so
    the recorder is safe on the dispatcher/harvester hot paths. When a
    recovery fires, the events for the poisoned requests are
    :meth:`snapshot`-ted into the recovery trace span, so a production
    429/504/recovery is explainable after the fact.

    Timestamps are monotonic-clock ms (offsets meaningful, absolutes
    not) — the same clock every other telemetry surface uses.
    """

    def __init__(self, capacity: int = 4096):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._events: "deque[dict]" = deque(maxlen=capacity)
        self._seq = 0

    def record(self, kind: str, **fields: Any) -> None:
        """Append one event (O(1)); ``fields`` must be JSON-safe."""
        with self._lock:
            self._seq += 1
            self._events.append({
                "seq": self._seq,
                "t_ms": round(time.monotonic() * 1e3, 3),
                "kind": kind,
                **fields,
            })

    def dump(
        self,
        n: Optional[int] = None,
        kind: Optional[str] = None,
        rid: Optional[str] = None,
        tenant: Optional[str] = None,
        phase: Optional[str] = None,
    ) -> List[dict]:
        """The newest ``n`` retained events (all when ``None``), oldest
        first; optionally filtered by ``kind``, request id, tenant tag
        (engines/batchers stamp request lifecycle events with the
        submitting tenant — the ``/debug/flight?tenant=`` postmortem
        filter), and/or serving ``phase`` tag (phase-split engines
        stamp their pool — prefill/decode — on every lifecycle event,
        and the router's ``handoff`` events carry both legs')."""
        with self._lock:
            events = list(self._events)
        if kind is not None:
            events = [e for e in events if e["kind"] == kind]
        if rid is not None:
            events = [
                e for e in events
                if e.get("rid") == rid or rid in e.get("rids", ())
            ]
        if tenant is not None:
            events = [e for e in events if e.get("tenant") == tenant]
        if phase is not None:
            events = [
                e for e in events
                if e.get("phase") == phase
                or phase in e.get("phases", ())
            ]
        if n is not None:
            n = int(n)
            events = events[-n:] if n > 0 else []
        return events

    def snapshot(self, rids: Sequence[str], limit: int = 100) -> List[dict]:
        """Events belonging to ``rids`` (newest ``limit``), for
        attaching to a recovery trace span."""
        wanted = set(rids)
        with self._lock:
            events = list(self._events)
        hits = [
            e for e in events
            if e.get("rid") in wanted or wanted & set(e.get("rids", ()))
        ]
        limit = int(limit)
        return hits[-limit:] if limit > 0 else []

    @property
    def total_recorded(self) -> int:
        with self._lock:
            return self._seq

    def stats(self) -> dict:
        with self._lock:
            retained = len(self._events)
            total = self._seq
        return {
            "capacity": self.capacity,
            "retained": retained,
            "total_recorded": total,
            "dropped": total - retained,
        }

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._seq = 0


# --------------------------------------------------------------------- #
# process-level gauges (standard Prometheus conventions)
# --------------------------------------------------------------------- #


def _process_start_time_s() -> float:
    """Epoch seconds this process started: /proc arithmetic on Linux
    (field 22 of /proc/self/stat is start-after-boot in clock ticks;
    btime in /proc/stat is boot epoch), falling back to this module's
    import time — close enough, telemetry imports early."""
    try:
        with open("/proc/self/stat") as f:
            # comm (field 2) may contain spaces/parens: split after it
            stat = f.read().rsplit(")", 1)[1].split()
        ticks = float(stat[19])  # field 22 overall; 20th after comm
        with open("/proc/stat") as f:
            btime = next(
                float(line.split()[1])
                for line in f
                if line.startswith("btime ")
            )
        return btime + ticks / os.sysconf("SC_CLK_TCK")
    except Exception:
        return _IMPORT_WALL_S


_IMPORT_WALL_S = time.time()

# one published build-info labelset per registry: a late jax import
# must not leave a second, stale child in the scrape
_build_info_published: Dict[int, Tuple[Tuple[str, ...], Any]] = {}
_build_info_lock = threading.Lock()


def publish_process_metrics(registry: Optional[MetricsRegistry] = None) -> None:
    """Register the standard process-level gauges on ``registry``
    (default: the process-global one): ``process_start_time_seconds``
    and ``unionml_tpu_build_info{version, jax_version, backend}`` = 1.

    Called by ``ServingApp.metrics_text()`` before every exposition, so
    any scraped registry carries them; label values resolve WITHOUT
    importing jax (``backend="unloaded"`` until something else loads
    it — this module must stay safe to import before jax), and a later
    resolution replaces the earlier child rather than leaving two."""
    reg = registry if registry is not None else _REGISTRY
    reg.gauge(
        "process_start_time_seconds",
        "Start time of the process since unix epoch in seconds.",
    ).set(_process_start_time_s())
    try:
        from unionml_tpu import __version__ as version
    except Exception:
        version = "unknown"
    jax_version, backend = "unloaded", "unloaded"
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        jax_version = str(getattr(jax_mod, "__version__", "unknown"))
        try:
            backend = str(jax_mod.default_backend())
        except Exception:
            backend = "unknown"
    labels = (str(version), jax_version, backend)
    family = reg.gauge(
        "unionml_tpu_build_info",
        "Build/runtime identity; value is always 1. Labels carry the "
        "package version, jax version, and active backend.",
        ("version", "jax_version", "backend"),
    )
    with _build_info_lock:
        prev = _build_info_published.get(id(reg))
        if prev is not None and prev[0] != labels:
            prev[1].set(0.0)  # supersede the pre-jax "unloaded" child
        child = family.labels(*labels)
        child.set(1.0)
        _build_info_published[id(reg)] = (labels, child)


# --------------------------------------------------------------------- #
# process-global defaults
# --------------------------------------------------------------------- #

_REGISTRY = MetricsRegistry()
_TRACER = TraceRecorder()
_FLIGHT = FlightRecorder()


def get_registry() -> MetricsRegistry:
    """The process-global default registry (what ``GET /metrics`` serves
    unless a component was built with an explicit one)."""
    return _REGISTRY


def get_tracer() -> TraceRecorder:
    """The process-global default trace recorder."""
    return _TRACER


def get_flight_recorder() -> FlightRecorder:
    """The process-global default flight recorder (what
    ``GET /debug/flight`` serves, and where engines/batchers record by
    default)."""
    return _FLIGHT


# the default registry always carries the process gauge, even for
# consumers that call exposition() directly without a ServingApp
publish_process_metrics(_REGISTRY)
