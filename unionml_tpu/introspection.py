"""XLA program introspection & continuous profiling.

The telemetry layer (:mod:`unionml_tpu.telemetry`) records what the
*host* saw — wall-clock latencies, queue depths — but nothing in the
stack could say what the *hardware* did: FLOPs issued, HBM bytes moved,
how many times XLA recompiled a hot program, or where device memory
went. This module closes that loop:

- :class:`ProgramTracker` — wraps the ``jit``/``pjit`` callables on the
  hot paths (engine prefill/decode/splice, batcher predict, trainer
  step) with a zero-copy shim that detects **compile events** (the
  executable cache grew during a call), records compile time and a
  recompile count, and — only on those rare events — runs
  ``jitted.lower(...).cost_analysis()`` over *abstract* arguments to
  capture per-program **flops** and **bytes accessed** (lowering alone:
  no second XLA compile, and donated/deleted buffers still carry the
  shape/dtype metadata the abstract trace needs). Steady-state calls
  pay only a cache-size read, one dict lookup, and counter increments —
  the introspection cost lives at compile time, off the serving path.
- **MFU / roofline gauges** — each tracked program keeps a bounded
  window of ``(t, cumulative flops, cumulative bytes)`` samples;
  ``unionml_program_mfu_ratio`` / ``unionml_program_hbm_ratio`` gauges
  divide the windowed achieved rate by the device peak from
  :data:`DEVICE_PEAKS` (per ``device_kind``, overridable for unknown
  chips via :data:`PEAK_FLOPS_ENV` / :data:`PEAK_HBM_ENV`).
- :func:`capture_profile` — the on-demand ``jax.profiler`` capture
  behind ``POST /debug/profile?seconds=N`` on both HTTP transports
  (building on :func:`unionml_tpu.diagnostics.trace`); one capture at a
  time (:class:`ProfileInProgress` maps to HTTP 409).
- :func:`device_memory_breakdown` — the ``GET /debug/memory`` body:
  per-device ``memory_stats()`` plus a live-buffer census from
  ``jax.live_arrays()`` grouped by dtype and top shapes (works on CPU,
  where ``memory_stats()`` is None but the buffer census is not).

Everything degrades gracefully: a non-jitted callable is tracked
opaquely (calls and wall time, no cost analysis), a backend without
profiling support captures an empty trace with a log line, and cost
analysis failures record zeros instead of failing the serving path.
CPU-testable end to end (``cost_analysis`` works on CPU jit).
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

from unionml_tpu._logging import logger
from unionml_tpu import telemetry

__all__ = [
    "DEVICE_PEAKS",
    "PEAK_FLOPS_ENV",
    "PEAK_HBM_ENV",
    "ProfileInProgress",
    "ProgramTracker",
    "capture_profile",
    "device_memory_breakdown",
    "resolve_device_peaks",
]

# env overrides for chips the table doesn't know (or partial overrides
# to correct a table entry): absolute FLOP/s and HBM GB/s
PEAK_FLOPS_ENV = "UNIONML_TPU_PEAK_FLOPS"
PEAK_HBM_ENV = "UNIONML_TPU_PEAK_HBM_GBPS"

# per-chip peaks: (dense bf16 FLOP/s, HBM bytes/s), keyed on a
# lowercase substring of `device.device_kind` (longest key wins, so
# "tpu v5 lite" matches before "tpu v5"). Sources: public TPU spec
# sheets; the CPU row is a NOMINAL placeholder so CPU test runs produce
# finite ratios — it is not a meaningful roofline.
DEVICE_PEAKS: Dict[str, Tuple[float, float]] = {
    "tpu v2": (45e12, 700e9),
    "tpu v3": (123e12, 900e9),
    "tpu v4": (275e12, 1228e9),
    "tpu v5 lite": (197e12, 819e9),
    "tpu v5e": (197e12, 819e9),
    "tpu v5p": (459e12, 2765e9),
    "tpu v5": (459e12, 2765e9),
    "tpu v6 lite": (918e12, 1640e9),
    "tpu v6e": (918e12, 1640e9),
    "cpu": (5e10, 2e10),
}


def resolve_device_peaks(device: Any = None) -> dict:
    """``{"platform", "kind", "peak_flops", "peak_bytes_per_s",
    "source"}`` for ``device`` (default: the first local device).

    Env overrides (:data:`PEAK_FLOPS_ENV` FLOP/s, :data:`PEAK_HBM_ENV`
    GB/s) win over the table — the escape hatch for chips the table
    doesn't know; either can be set alone. ``source`` is ``env``,
    ``table``, or ``unknown`` (no match: peaks are ``None`` and the
    MFU gauges report 0 rather than a made-up ratio)."""
    platform, kind = "unknown", "unknown"
    try:
        if device is None:
            import jax

            device = jax.local_devices()[0]
        platform = str(getattr(device, "platform", "unknown"))
        kind = str(getattr(device, "device_kind", platform))
    except Exception as exc:  # no backend: peaks resolve from env only
        logger.info(f"device peak resolution: no device ({exc!r})")
    flops: Optional[float] = None
    bandwidth: Optional[float] = None
    source = "unknown"
    lowered = kind.lower()
    for key in sorted(DEVICE_PEAKS, key=len, reverse=True):
        if key in lowered or key in platform.lower():
            flops, bandwidth = DEVICE_PEAKS[key]
            source = "table"
            break
    env_flops = os.environ.get(PEAK_FLOPS_ENV)
    env_hbm = os.environ.get(PEAK_HBM_ENV)
    if env_flops or env_hbm:
        try:
            if env_flops:
                flops = float(env_flops)
            if env_hbm:
                bandwidth = float(env_hbm) * 1e9
            source = "env"
        except ValueError:
            logger.info(
                f"ignoring malformed peak override "
                f"{PEAK_FLOPS_ENV}={env_flops!r} {PEAK_HBM_ENV}={env_hbm!r}"
            )
    return {
        "platform": platform,
        "kind": kind,
        "peak_flops": flops,
        "peak_bytes_per_s": bandwidth,
        "source": source,
    }


def _abstract_args(args: tuple, kwargs: dict):
    """Shape/dtype skeletons for an AOT ``lower()`` — works even on
    donated (deleted) device buffers, whose metadata survives deletion;
    non-array leaves (static ints, None) pass through unchanged."""
    import jax

    def to_sds(leaf):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            return leaf
        return jax.ShapeDtypeStruct(tuple(shape), dtype)

    return (
        jax.tree_util.tree_map(to_sds, args),
        jax.tree_util.tree_map(to_sds, kwargs),
    )


class _Program:
    """Per-key tracking state (guarded by the tracker lock)."""

    __slots__ = (
        "key", "calls", "compiles", "cum_flops", "cum_bytes",
        "cost_by_sig", "last_cost", "window", "last_t",
        "m_calls", "m_compiles", "m_flops", "m_bytes", "h_compile",
    )

    def __init__(self, key: str):
        self.key = key
        self.calls = 0
        self.compiles = 0
        self.cum_flops = 0.0
        self.cum_bytes = 0.0
        # signature -> (flops, bytes accessed) from cost analysis; the
        # sig is whatever the program's sig_fn returns (a bucket shape,
        # a static length) — None for single-shape programs
        self.cost_by_sig: Dict[Any, Tuple[float, float]] = {}
        self.last_cost: Tuple[float, float] = (0.0, 0.0)
        self.window: "deque[Tuple[float, float, float]]" = deque(maxlen=256)
        self.last_t = 0.0


class ProgramTracker:
    """Cost-analysis registry over a component's compiled programs.

    ``wrap(key, fn, sig_fn=...)`` returns a drop-in callable. For a
    jitted ``fn`` the wrapper detects compiles via ``_cache_size()``
    growth and records the new executable's ``cost_analysis()`` (flops,
    bytes accessed) keyed by ``sig_fn``'s cheap per-call signature (a
    bucket shape — NOT a full aval tree, which would put a tree
    traversal on the hot path); steady-state calls attribute that
    signature's flops/bytes to the cumulative counters and the MFU
    window. A non-jitted ``fn`` is tracked opaquely (calls only).

    All series land in the shared telemetry registry labeled
    ``{component, program}``; :meth:`stats` is the ``stats()
    ["programs"]`` view.
    """

    WINDOW_S = 60.0

    def __init__(
        self,
        registry: Optional[telemetry.MetricsRegistry] = None,
        component: str = "program",
        window_s: float = WINDOW_S,
        on_compile: Optional[Callable[[str, float], None]] = None,
    ):
        self._registry = (
            registry if registry is not None else telemetry.get_registry()
        )
        self.component = component
        self.window_s = float(window_s)
        # compile-event push seam: called as on_compile(key, call_ms)
        # AFTER the compiling call returns — the goodput tracker
        # (unionml_tpu.goodput) subscribes to debit compile time out of
        # the compute bucket. Exceptions are swallowed: an observer bug
        # must never fail the hot path.
        self.on_compile = on_compile
        self._lock = threading.Lock()
        self._programs: Dict[str, _Program] = {}
        self._peaks: Optional[dict] = None
        R = self._registry
        labels = ("component", "program")
        self._f_calls = R.counter(
            "unionml_program_calls_total",
            "Dispatches of a tracked compiled program.", labels,
        )
        self._f_compiles = R.counter(
            "unionml_program_compiles_total",
            "XLA compile events per tracked program (a count above the "
            "expected shape set = recompiles).", labels,
        )
        self._f_flops = R.counter(
            "unionml_program_flops_total",
            "FLOPs dispatched per XLA cost analysis.", labels,
        )
        self._f_bytes = R.counter(
            "unionml_program_bytes_total",
            "HBM bytes accessed per XLA cost analysis.", labels,
        )
        self._f_compile_ms = R.histogram(
            "unionml_program_compile_ms",
            "Wall time of calls that compiled (trace + XLA compile + "
            "first run).", labels,
        )
        self._f_mfu = R.gauge(
            "unionml_program_mfu_ratio",
            "Windowed achieved FLOP/s over the device peak "
            "(model-flops utilization; 0 when idle or peak unknown).",
            labels,
        )
        self._f_hbm = R.gauge(
            "unionml_program_hbm_ratio",
            "Windowed achieved bytes/s over peak HBM bandwidth "
            "(roofline memory utilization; 0 when idle or peak "
            "unknown).", labels,
        )

    # ------------------------------------------------------------------ #

    def _get(self, key: str) -> _Program:
        with self._lock:
            prog = self._programs.get(key)
            if prog is None:
                prog = _Program(key)
                lbl = (self.component, key)
                prog.m_calls = self._f_calls.labels(*lbl)
                prog.m_compiles = self._f_compiles.labels(*lbl)
                prog.m_flops = self._f_flops.labels(*lbl)
                prog.m_bytes = self._f_bytes.labels(*lbl)
                prog.h_compile = self._f_compile_ms.labels(*lbl)
                self._f_mfu.labels(*lbl).set_function(
                    lambda p=prog: self._utilization(p)[0]
                )
                self._f_hbm.labels(*lbl).set_function(
                    lambda p=prog: self._utilization(p)[1]
                )
                self._programs[key] = prog
            return prog

    def wrap(
        self,
        key: str,
        fn: Callable,
        sig_fn: Optional[Callable[..., Any]] = None,
    ) -> Callable:
        """Instrument ``fn`` under ``key``. ``sig_fn(*args, **kwargs)``
        must be CHEAP (one shape attribute, a static kwarg) and only
        distinct enough to separate the executables this one callable
        compiles (e.g. the token-bucket shape for prefill); ``None``
        declares a single-executable program."""
        prog = self._get(key)
        jitted = hasattr(fn, "_cache_size") and hasattr(fn, "lower")

        def wrapper(*args, **kwargs):
            before = fn._cache_size() if jitted else -1
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            dt_ms = (time.perf_counter() - t0) * 1e3
            sig = None
            if sig_fn is not None:
                try:
                    sig = sig_fn(*args, **kwargs)
                except Exception:
                    sig = None
            if jitted and fn._cache_size() > before:
                self._on_compile(prog, fn, args, kwargs, sig, dt_ms)
            else:
                self._on_call(prog, sig)
            return out

        wrapper.__wrapped__ = fn
        wrapper.program_key = key
        return wrapper

    def _on_compile(
        self, prog: _Program, fn, args, kwargs, sig, dt_ms: float
    ) -> None:
        """Compile event (rare, off the steady-state path): record the
        compile and run the abstract-args cost analysis for the new
        signature. Lowering re-traces but never re-compiles, and the
        abstract skeleton sidesteps donated buffers."""
        cost = (0.0, 0.0)
        try:
            a_args, a_kwargs = _abstract_args(args, kwargs)
            analysis = fn.lower(*a_args, **a_kwargs).cost_analysis()
            if isinstance(analysis, (list, tuple)):
                analysis = analysis[0] if analysis else {}
            cost = (
                float(analysis.get("flops", 0.0) or 0.0),
                float(analysis.get("bytes accessed", 0.0) or 0.0),
            )
        except Exception as exc:
            logger.info(f"cost analysis unavailable for {prog.key}: {exc!r}")
        with self._lock:
            prog.compiles += 1
            prog.cost_by_sig[sig] = cost
            prog.last_cost = cost
        prog.m_compiles.inc()
        prog.h_compile.observe(dt_ms)
        if self.on_compile is not None:
            try:
                self.on_compile(prog.key, dt_ms)
            except Exception:
                pass
        self._account(prog, cost)

    def cost(self, key: str, sig: Any = None) -> Tuple[float, float]:
        """Last-known ``(flops, bytes)`` of one dispatch of program
        ``key`` at signature ``sig`` (falling back to the program's
        last compiled cost; ``(0, 0)`` for untracked programs) — the
        per-dispatch numerator the usage ledger splits across tenants
        (docs/observability.md "Usage metering & cost attribution")."""
        with self._lock:
            prog = self._programs.get(key)
            if prog is None:
                return (0.0, 0.0)
            return prog.cost_by_sig.get(sig, prog.last_cost)

    def _on_call(self, prog: _Program, sig) -> None:
        with self._lock:
            cost = prog.cost_by_sig.get(sig, prog.last_cost)
        self._account(prog, cost)

    def _account(self, prog: _Program, cost: Tuple[float, float]) -> None:
        now = time.monotonic()
        flops, nbytes = cost
        with self._lock:
            prog.calls += 1
            prog.cum_flops += flops
            prog.cum_bytes += nbytes
            prog.window.append((now, prog.cum_flops, prog.cum_bytes))
            while (
                len(prog.window) > 2
                and now - prog.window[0][0] > self.window_s
            ):
                prog.window.popleft()
            prog.last_t = now
        prog.m_calls.inc()
        if flops:
            prog.m_flops.inc(flops)
        if nbytes:
            prog.m_bytes.inc(nbytes)

    # ------------------------------------------------------------------ #

    def peaks(self) -> dict:
        """Device peaks, resolved once per tracker (jax is loaded by the
        time any tracked program has compiled)."""
        with self._lock:
            if self._peaks is None:
                self._peaks = resolve_device_peaks()
            return self._peaks

    def _rates(self, prog: _Program) -> Tuple[float, float]:
        """Windowed achieved (FLOP/s, bytes/s); 0 when idle (no
        dispatch within the window) or under 2 samples."""
        now = time.monotonic()
        with self._lock:
            if len(prog.window) < 2 or now - prog.last_t > self.window_s:
                return 0.0, 0.0
            t0, f0, b0 = prog.window[0]
            t1, f1, b1 = prog.window[-1]
        dt = t1 - t0
        if dt <= 0:
            return 0.0, 0.0
        return (f1 - f0) / dt, (b1 - b0) / dt

    def _utilization(self, prog: _Program) -> Tuple[float, float]:
        """(MFU, HBM-roofline) ratios for the gauges; 0 when the peak
        is unknown rather than a fabricated ratio."""
        flops_s, bytes_s = self._rates(prog)
        peaks = self.peaks()
        mfu = (
            flops_s / peaks["peak_flops"] if peaks["peak_flops"] else 0.0
        )
        hbm = (
            bytes_s / peaks["peak_bytes_per_s"]
            if peaks["peak_bytes_per_s"] else 0.0
        )
        return mfu, hbm

    def stats(self) -> dict:
        """The ``stats()["programs"]`` view: per program — calls,
        compiles, compile-time summary, flops/bytes per call and total,
        windowed achieved rates, and the MFU/roofline ratios — plus a
        ``device`` entry naming the peaks they are measured against."""
        peaks = self.peaks()
        out: dict = {"device": dict(peaks)}
        with self._lock:
            programs = list(self._programs.values())
        for prog in programs:
            mfu, hbm = self._utilization(prog)
            flops_s, bytes_s = self._rates(prog)
            with self._lock:
                entry = {
                    "calls": prog.calls,
                    "compiles": prog.compiles,
                    "flops_per_call": prog.last_cost[0],
                    "bytes_per_call": prog.last_cost[1],
                    "flops_total": prog.cum_flops,
                    "bytes_total": prog.cum_bytes,
                }
            summary = prog.h_compile.summary()
            if summary:
                entry["compile_ms"] = summary
            entry["achieved_flops_per_s"] = round(flops_s, 1)
            entry["achieved_bytes_per_s"] = round(bytes_s, 1)
            entry["mfu"] = round(mfu, 6)
            entry["hbm_utilization"] = round(hbm, 6)
            out[prog.key] = entry
        return out

    def reset(self) -> None:
        """Zero cumulative counters and windows (benchmarks call this
        between phases); compiled-cost signatures are kept — they
        describe executables that still exist."""
        with self._lock:
            programs = list(self._programs.values())
        for prog in programs:
            with self._lock:
                prog.calls = 0
                prog.compiles = 0
                prog.cum_flops = 0.0
                prog.cum_bytes = 0.0
                prog.window.clear()
                prog.last_t = 0.0
            for m in (prog.m_calls, prog.m_compiles, prog.m_flops,
                      prog.m_bytes, prog.h_compile):
                m.reset()


# --------------------------------------------------------------------- #
# on-demand profiler capture (POST /debug/profile)
# --------------------------------------------------------------------- #


class ProfileInProgress(RuntimeError):
    """A capture is already running (the transports answer 409): the
    profiler is a process-global singleton and nested traces corrupt
    the artifact."""


_capture_lock = threading.Lock()

MAX_CAPTURE_SECONDS = 120.0


def capture_profile(
    seconds: float = 2.0, log_dir: Optional[str] = None
) -> dict:
    """Capture a ``jax.profiler`` trace for ``seconds`` (clamped to
    :data:`MAX_CAPTURE_SECONDS`) and return the artifact directory.

    Blocks the calling thread for the capture window (the transports
    serve it from a request thread, so in-flight traffic keeps running
    — that traffic is exactly what the trace is for). Builds on
    :func:`unionml_tpu.diagnostics.trace`, so an unsupported backend
    degrades to an empty artifact directory with a log line instead of
    a 500. One capture at a time: raises :class:`ProfileInProgress`
    when another is running."""
    seconds = float(seconds)
    if not seconds > 0:
        raise ValueError(f"seconds must be positive, got {seconds}")
    seconds = min(seconds, MAX_CAPTURE_SECONDS)
    if not _capture_lock.acquire(blocking=False):
        raise ProfileInProgress(
            "a profiler capture is already in progress; retry when it "
            "finishes"
        )
    try:
        from unionml_tpu.diagnostics import trace

        if log_dir is None:
            log_dir = tempfile.mkdtemp(prefix="unionml-tpu-profile-")
        t0 = time.perf_counter()
        with trace(log_dir):
            time.sleep(seconds)
        captured_s = time.perf_counter() - t0
        files = []
        for root, _, names in os.walk(log_dir):
            for name in names:
                files.append(
                    os.path.relpath(os.path.join(root, name), log_dir)
                )
        return {
            "trace_dir": log_dir,
            "seconds": round(captured_s, 3),
            "file_count": len(files),
            "files": sorted(files)[:50],
        }
    finally:
        _capture_lock.release()


# --------------------------------------------------------------------- #
# device-memory breakdown (GET /debug/memory)
# --------------------------------------------------------------------- #


def device_memory_breakdown(top: int = 10) -> dict:
    """Per-device memory truth: ``device.memory_stats()`` (TPU/GPU; CPU
    backends report none) plus a live-buffer census from
    ``jax.live_arrays()`` — total bytes, per-dtype totals, and the
    ``top`` largest (shape, dtype) groups, which is where a leaked KV
    cache or a forgotten checkpoint tree shows up by name. Also reports
    the size of the pprof ``device_memory_profile`` artifact (the
    heavyweight offline view) without shipping its bytes."""
    import jax

    devices = []
    for device in jax.local_devices():
        stats = None
        try:
            stats = device.memory_stats()
        except Exception:
            stats = None
        devices.append({
            "id": int(device.id),
            "platform": str(device.platform),
            "kind": str(getattr(device, "device_kind", device.platform)),
            "memory_stats": {
                str(k): int(v) for k, v in (stats or {}).items()
                if isinstance(v, (int, float))
            },
        })
    groups: Dict[Tuple[str, Tuple[int, ...]], Dict[str, int]] = {}
    by_dtype: Dict[str, int] = {}
    total_bytes = 0
    count = 0
    for arr in jax.live_arrays():
        try:
            nbytes = int(arr.nbytes)
            dtype = str(arr.dtype)
            shape = tuple(int(s) for s in arr.shape)
        except Exception:
            continue  # deleted/exotic arrays: skip, never fail the scrape
        count += 1
        total_bytes += nbytes
        by_dtype[dtype] = by_dtype.get(dtype, 0) + nbytes
        group = groups.setdefault(
            (dtype, shape), {"count": 0, "bytes": 0}
        )
        group["count"] += 1
        group["bytes"] += nbytes
    top_groups = [
        {
            "dtype": dtype,
            "shape": list(shape),
            "count": info["count"],
            "bytes": info["bytes"],
        }
        for (dtype, shape), info in sorted(
            groups.items(), key=lambda kv: kv[1]["bytes"], reverse=True
        )[: max(0, int(top))]
    ]
    profile_bytes = None
    try:
        profile_bytes = len(jax.profiler.device_memory_profile())
    except Exception:
        pass
    return {
        "devices": devices,
        "live_arrays": {
            "count": count,
            "bytes": total_bytes,
            "by_dtype": by_dtype,
            "top": top_groups,
        },
        "device_memory_profile_bytes": profile_bytes,
    }
