"""Compiled stages and workflows: the execution substrate.

This module replaces the reference's flytekit dependency. The reference
compiles user functions into flytekit tasks via ``inner_task``
(reference: unionml/utils.py:10-59) and assembles them into flytekit
workflows (reference: unionml/model.py:292-338). Here a compiled unit is a
:class:`Stage`:

- **named** ``{object_name}.{fn_name}`` (reference: utils.py:58),
- **directly callable** — the local executor is plain Python, which doubles
  as the unit-test fake (reference test strategy, tests/unit/test_model.py),
- **resource-annotated** (:class:`unionml_tpu.defaults.Resources`),
- **cacheable** — ``cache=True, cache_version=...`` produces a
  content-addressed on-disk cache, replicating the flytekit caching knob the
  quickdraw template uses (reference: templates/quickdraw/.../app.py:18-62),
- **rehydratable** — a stage serializes as ``(module, variable,
  stage_method)`` and is regenerated remotely by re-importing the app module
  (reference: unionml/task_resolver.py:16-31).

A :class:`Workflow` is a plain-Python DAG of stages with named inputs and
outputs; calling it executes the DAG in-process. Device placement happens
*inside* stage bodies (jit/pjit over a mesh) — the workflow layer is
host-side orchestration only, so XLA owns all on-device scheduling.
"""

from __future__ import annotations

import functools
import hashlib
import os
import pickle
from dataclasses import dataclass, field
from inspect import Parameter, Signature

from unionml_tpu.type_guards import signature
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from unionml_tpu._logging import logger
from unionml_tpu.defaults import DEFAULT_RESOURCES, Resources
from unionml_tpu.tracking import load_instance

CACHE_DIR_ENV = "UNIONML_TPU_CACHE_DIR"
DEFAULT_CACHE_DIR = "~/.cache/unionml_tpu/stages"


def _stable_hash(obj: Any) -> str:
    """Content hash of arbitrary Python objects for stage caching."""
    try:
        import joblib

        return joblib.hash(obj) or "none"
    except Exception:
        try:
            return hashlib.sha256(pickle.dumps(obj)).hexdigest()
        except Exception:
            return hashlib.sha256(repr(obj).encode()).hexdigest()


@dataclass
class StageRef:
    """Serializable pointer to a dynamically generated stage.

    Reference: unionml/task_resolver.py:23-31 — ``loader_args`` records the
    app module, the Dataset/Model variable name, and the generator method.
    """

    module: str
    var_name: str
    stage_method: str

    def load(self) -> "Stage":
        instance = load_instance(self.module, self.var_name)
        return getattr(instance, self.stage_method)()


class Stage:
    """A named, cached, resource-annotated compiled unit of work."""

    def __init__(
        self,
        fn: Callable,
        *,
        name: str,
        parameters: Sequence[Parameter],
        return_annotation: Any = Signature.empty,
        resources: Resources = DEFAULT_RESOURCES,
        cache: bool = False,
        cache_version: str = "0",
        ref: Optional[StageRef] = None,
        owner: Any = None,
    ):
        self._fn = fn
        self.name = name
        self.resources = resources
        self.cache = cache
        self.cache_version = cache_version
        self.ref = ref
        # backref so a stage can be traced to its Dataset/Model
        # (reference: utils.py:33 __unionml_object__)
        self.__unionml_object__ = owner
        params = [
            p.replace(kind=Parameter.KEYWORD_ONLY)
            if p.kind in (Parameter.POSITIONAL_ONLY, Parameter.POSITIONAL_OR_KEYWORD)
            else p
            for p in parameters
        ]
        self.__signature__ = Signature(params, return_annotation=return_annotation)
        self.__name__ = name
        functools.update_wrapper(self, fn, assigned=("__doc__", "__module__"))
        self.__annotations__ = {p.name: p.annotation for p in params}
        if return_annotation is not Signature.empty:
            self.__annotations__["return"] = return_annotation

    # -- interface introspection (reference asserts task input/output types:
    #    tests/unit/test_model.py:25-44)
    @property
    def input_types(self) -> Dict[str, Any]:
        return {
            k: p.annotation for k, p in self.__signature__.parameters.items()
        }

    @property
    def output_type(self) -> Any:
        return self.__signature__.return_annotation

    def _cache_path(self, key: str) -> Path:
        root = Path(os.environ.get(CACHE_DIR_ENV, DEFAULT_CACHE_DIR)).expanduser()
        return root / self.name / self.cache_version / f"{key}.pkl"

    def __call__(self, **kwargs) -> Any:
        bound = self.__signature__.bind(**kwargs)
        bound.apply_defaults()
        if self.cache:
            key = _stable_hash((self.name, self.cache_version, bound.arguments))
            path = self._cache_path(key)
            if path.exists():
                logger.info(f"stage {self.name}: cache hit ({key[:12]})")
                with open(path, "rb") as f:
                    return pickle.load(f)
            result = self._fn(**bound.arguments)
            try:
                path.parent.mkdir(parents=True, exist_ok=True)
                with open(path, "wb") as f:
                    pickle.dump(result, f)
            except Exception as exc:  # non-picklable results stay uncached
                logger.info(f"stage {self.name}: result not cacheable ({exc})")
            return result
        return self._fn(**bound.arguments)

    def __repr__(self) -> str:
        return f"Stage(name={self.name!r}, inputs={list(self.input_types)})"


def stage_from_fn(
    fn: Callable,
    *,
    owner: Any,
    name: Optional[str] = None,
    parameters: Optional[Sequence[Parameter]] = None,
    return_annotation: Any = None,
    stage_method: Optional[str] = None,
    resources: Optional[Resources] = None,
    cache: bool = False,
    cache_version: str = "0",
) -> Stage:
    """Compile a function into a :class:`Stage` owned by ``owner``.

    The synthesized name is ``{owner.name}.{fn.__name__}``
    (reference: utils.py:58) and the stage records a :class:`StageRef` for
    remote rehydration when the owner is module-tracked.
    """
    sig = signature(fn)
    ref = None
    if stage_method is not None:
        try:
            module, var = owner.loader_path()
            ref = StageRef(module=module, var_name=var, stage_method=stage_method)
        except Exception:
            ref = None  # interactively defined objects can't be rehydrated
    return Stage(
        fn,
        name=name or f"{owner.name}.{fn.__name__}",
        parameters=parameters if parameters is not None else list(sig.parameters.values()),
        return_annotation=(
            return_annotation if return_annotation is not None else sig.return_annotation
        ),
        resources=resources or DEFAULT_RESOURCES,
        cache=cache,
        cache_version=cache_version,
        ref=ref,
        owner=owner,
    )


@dataclass(frozen=True)
class Literal:
    """Wrap a literal string value in a workflow binding (bare strings name
    workflow inputs)."""

    value: Any


@dataclass
class WorkflowNode:
    """One stage invocation in a workflow DAG."""

    stage: Stage
    # mapping of stage-kwarg name -> workflow input name or (node_idx, key)
    bindings: Dict[str, Any] = field(default_factory=dict)
    output_name: Optional[str] = None


class Workflow:
    """A named, directly-callable DAG of stages.

    Reference analog: the flytekit ``Workflow`` assembled at
    unionml/model.py:292-338. Inputs are declared with names + types;
    each node binds stage kwargs either to workflow inputs or to upstream
    node outputs; outputs select node results by name.
    """

    def __init__(self, name: str):
        self.name = name
        self.inputs: Dict[str, Tuple[Any, Any]] = {}  # name -> (type, default)
        self.nodes: List[WorkflowNode] = []
        self.outputs: Dict[str, Tuple[int, Optional[Any]]] = {}  # name -> (node idx, selector)

    _EMPTY = object()

    def add_input(self, name: str, annotation: Any = Any, default: Any = _EMPTY) -> str:
        self.inputs[name] = (annotation, default)
        return name

    def add_node(self, stage: Stage, bindings: Dict[str, Any]) -> int:
        self.nodes.append(WorkflowNode(stage=stage, bindings=bindings))
        return len(self.nodes) - 1

    def add_output(self, name: str, node_idx: int, selector: Optional[Callable] = None):
        self.outputs[name] = (node_idx, selector)

    def __call__(self, **kwargs) -> Any:
        # resolve inputs with defaults
        values: Dict[str, Any] = {}
        for name, (_, default) in self.inputs.items():
            if name in kwargs:
                values[name] = kwargs.pop(name)
            elif default is not self._EMPTY:
                values[name] = default
            else:
                raise TypeError(f"workflow {self.name!r} missing required input {name!r}")
        if kwargs:
            raise TypeError(f"workflow {self.name!r} got unexpected inputs {sorted(kwargs)}")

        node_results: List[Any] = []
        for node in self.nodes:
            stage_kwargs = {}
            for arg_name, binding in node.bindings.items():
                if isinstance(binding, tuple) and len(binding) == 2 and isinstance(binding[0], int):
                    upstream, selector = binding
                    result = node_results[upstream]
                    stage_kwargs[arg_name] = selector(result) if callable(selector) else result
                elif isinstance(binding, str):
                    # string bindings always name a workflow input; a typo is
                    # an assembly error, not a literal value
                    if binding not in values:
                        raise TypeError(
                            f"workflow {self.name!r}: node argument {arg_name!r} is "
                            f"bound to unknown input {binding!r} (inputs: "
                            f"{sorted(values)}). Use Literal(...) for literal strings."
                        )
                    stage_kwargs[arg_name] = values[binding]
                elif isinstance(binding, Literal):
                    stage_kwargs[arg_name] = binding.value
                else:
                    stage_kwargs[arg_name] = binding  # literal
            node_results.append(node.stage(**stage_kwargs))

        if not self.outputs:
            return node_results[-1] if node_results else None
        out = {
            name: (selector(node_results[idx]) if callable(selector) else node_results[idx])
            for name, (idx, selector) in self.outputs.items()
        }
        if len(out) == 1:
            return next(iter(out.values()))
        return out

    def __repr__(self) -> str:
        return (
            f"Workflow(name={self.name!r}, inputs={list(self.inputs)}, "
            f"nodes={[n.stage.name for n in self.nodes]}, outputs={list(self.outputs)})"
        )
