"""CLI: init / deploy / train / predict / list-model-versions / fetch-model / serve.

Command-for-command parity with reference unionml/cli.py:26-212 (typer →
click, which is dependency-available; uvicorn's role is played by the
stdlib serving transport). The ``serve`` command exports ``--model-path``
via ``UNIONML_MODEL_PATH`` exactly like the reference's patched uvicorn
callback (reference: cli.py:172-212).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
from pathlib import Path

import click

TEMPLATES_DIR = Path(__file__).parent / "templates"
APP_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


@click.group()
def app():
    """unionml-tpu: TPU-native declarative ML microservices."""


@app.command()
@click.argument("app_name")
@click.option("--template", "-t", default="basic",
              type=click.Choice(
                  [p.name for p in sorted(TEMPLATES_DIR.iterdir())]
                  if TEMPLATES_DIR.exists() else ["basic"]
              ),
              help="project template")
def init(app_name: str, template: str):
    """Scaffold a new app (reference: cli.py:33-51 + cookiecutter hooks)."""
    # pre-gen name validation (reference: templates/common/hooks/pre_gen_project.py)
    if not APP_NAME_RE.match(app_name):
        raise click.ClickException(
            f"app name {app_name!r} must be a valid Python identifier"
        )
    src = TEMPLATES_DIR / template
    dest = Path.cwd() / app_name
    if dest.exists():
        raise click.ClickException(f"directory {dest} already exists")
    dest.mkdir(parents=True)
    for f in sorted(src.rglob("*")):
        if f.is_dir() or "__pycache__" in f.parts:
            # bytecode caches appear whenever a template app gets imported
            # (tests, compileall) and must never reach the scaffold
            continue
        rel = Path(str(f.relative_to(src)).replace("{{app_name}}", app_name))
        target = dest / rel
        target.parent.mkdir(parents=True, exist_ok=True)
        try:
            # explicit utf-8: with the locale default, a non-ASCII TEXT
            # template could decode-fail and skip {{app_name}} substitution
            target.write_text(
                f.read_text(encoding="utf-8").replace("{{app_name}}", app_name),
                encoding="utf-8",
            )
        except UnicodeDecodeError:
            target.write_bytes(f.read_bytes())  # binary assets copy verbatim
    # post-gen: git init + initial commit (reference: post_gen_project.py)
    try:
        quiet = {"capture_output": True, "cwd": dest}
        subprocess.run(["git", "init", "-q"], check=True, **quiet)
        subprocess.run(["git", "add", "."], check=True, **quiet)
        subprocess.run(
            ["git", "commit", "-q", "-m", f"initialize {app_name} from {template} template"],
            check=False, **quiet,
        )
    except (subprocess.CalledProcessError, FileNotFoundError):
        pass
    click.echo(f"initialized {app_name} from template {template!r} at {dest}")


def _get_model(app_str: str):
    sys.path.insert(0, os.getcwd())
    from unionml_tpu.remote import get_model

    return get_model(app_str)


@app.command()
@click.argument("app_str", metavar="APP")
@click.option("--app-version", default=None)
@click.option("--allow-uncommitted", is_flag=True, default=False)
@click.option("--patch", is_flag=True, default=False, help="fast source-only redeploy")
def deploy(app_str: str, app_version, allow_uncommitted: bool, patch: bool):
    """Deploy an app to the backend (reference: cli.py:54-82)."""
    model = _get_model(app_str)
    version = model.remote_deploy(
        app_version=app_version, allow_uncommitted=allow_uncommitted, patch=patch
    )
    click.echo(f"deployed {model.name} version {version}")


@app.command()
@click.argument("app_str", metavar="APP")
@click.option("--inputs", "-i", default="{}", help="JSON of train inputs")
@click.option("--app-version", default=None)
def train(app_str: str, inputs: str, app_version):
    """Train on the backend (reference: cli.py:85-103)."""
    model = _get_model(app_str)
    kwargs = json.loads(inputs)
    artifact = model.remote_train(app_version=app_version, wait=True, **kwargs)
    click.echo(f"trained model: {type(artifact.model_object).__name__}")
    click.echo(f"metrics: {artifact.metrics}")


@app.command()
@click.argument("app_str", metavar="APP")
@click.option("--inputs", "-i", default=None, help="JSON of reader kwargs")
@click.option("--features", "-f", default=None, help="path to a features file")
@click.option("--app-version", default=None)
@click.option("--model-version", default="latest")
def predict(app_str: str, inputs, features, app_version, model_version):
    """Predict on the backend (reference: cli.py:106-127)."""
    model = _get_model(app_str)
    kwargs = json.loads(inputs) if inputs else {}
    feats = None
    if features is not None:
        feats = model.dataset.get_features(features)
    preds = model.remote_predict(
        app_version=app_version, model_version=model_version,
        wait=True, features=feats, **kwargs,
    )
    click.echo(json.dumps(preds, default=str))


@app.command(name="list-model-versions")
@click.argument("app_str", metavar="APP")
@click.option("--app-version", default=None)
@click.option("--limit", default=10)
def list_model_versions(app_str: str, app_version, limit: int):
    """List model versions = train executions (reference: cli.py:130-144)."""
    model = _get_model(app_str)
    for v in model.remote_list_model_versions(app_version=app_version, limit=limit):
        click.echo(v)


@app.command(name="fetch-model")
@click.argument("app_str", metavar="APP")
@click.option("--output", "-o", required=True, help="path to save the model artifact")
@click.option("--app-version", default=None)
@click.option("--model-version", default="latest")
def fetch_model(app_str: str, output: str, app_version, model_version: str):
    """Fetch a model artifact from the registry (reference: cli.py:147-165)."""
    model = _get_model(app_str)
    from unionml_tpu.remote import load_latest_artifact

    load_latest_artifact(model, app_version=app_version, model_version=model_version)
    model.save(output)
    click.echo(f"saved model artifact to {output}")


@app.command()
@click.argument("app_str", metavar="APP")
@click.option("--model-path", default=None, help="path to a local model artifact")
@click.option("--host", default="127.0.0.1")
@click.option("--port", default=8000)
@click.option("--batch/--no-batch", default=False, help="enable the on-device micro-batcher")
@click.option(
    "--row-lists/--no-row-lists", default=False,
    help="batch plain lists of ragged rows (LLM token-id prompts) by list concat",
)
def serve(app_str: str, model_path, host: str, port: int, batch: bool, row_lists: bool):
    """Serve an app over HTTP (reference: cli.py:172-212).

    APP is ``module:variable`` naming a Model or a ServingApp. A
    ServingApp constructed with ``stream=`` (e.g. wrapping
    ``DecodeEngine.generate_stream``) additionally serves SSE token
    streaming at ``POST /predict/stream``.
    """
    if model_path is not None:
        if not Path(model_path).exists():
            raise click.ClickException(f"model path {model_path} does not exist")
        os.environ["UNIONML_MODEL_PATH"] = str(model_path)
    target = _get_model(app_str)
    from unionml_tpu.model import Model
    from unionml_tpu.serving.http import ServingApp

    if row_lists and not batch:
        batch = True  # row-list mode only exists inside the micro-batcher
        click.echo("--row-lists implies --batch; enabling the micro-batcher")
    if isinstance(target, Model):
        serving = ServingApp(target, batch=batch, row_lists=row_lists)
    elif isinstance(target, ServingApp):
        if batch or row_lists:
            click.echo(
                "warning: --batch/--row-lists are ignored when APP is a "
                "pre-built ServingApp — its own batcher settings take "
                "precedence (construct the ServingApp with batch=/row_lists=)"
            )
        serving = target
    else:
        raise click.ClickException(
            f"{app_str} must resolve to a unionml_tpu Model or ServingApp, "
            f"got {type(target)}"
        )
    serving.serve(host=host, port=port, blocking=True)


if __name__ == "__main__":
    app()
