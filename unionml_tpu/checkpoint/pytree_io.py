"""Single-file pytree artifacts (the Model.save/load path).

Replaces the reference's joblib single-file artifact
(reference: unionml/model.py:940-946) for JAX model objects: leaves are
serialized with flax's msgpack wire format plus a JSON header carrying
hyperparameters, so an artifact is self-describing and loadable in a fresh
process given the app's ``init`` to rebuild the pytree structure.
"""

from __future__ import annotations

import json
import os
import struct
import time
from typing import IO, Any, Callable, Optional, Union

from unionml_tpu.checkpoint._metrics import checkpoint_metrics

_MAGIC = b"UTPU1"


def _open(file: Union[str, os.PathLike, IO], mode: str):
    if hasattr(file, "write") or hasattr(file, "read"):
        return file, False
    return open(file, mode), True


def save_pytree(pytree: Any, hyperparameters: Optional[dict], file: Union[str, os.PathLike, IO]) -> None:
    """Serialize ``pytree`` + hyperparameters to ``file``."""
    from flax import serialization

    t0 = time.perf_counter()
    payload = serialization.to_bytes(pytree)
    header = json.dumps({"hyperparameters": hyperparameters}).encode()
    f, should_close = _open(file, "wb")
    try:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", len(header)))
        f.write(header)
        f.write(payload)
    finally:
        if should_close:
            f.close()
    metrics = checkpoint_metrics()
    metrics["save_ms"].labels("pytree").observe(
        (time.perf_counter() - t0) * 1e3
    )
    metrics["save_bytes"].labels("pytree").inc(
        len(_MAGIC) + 8 + len(header) + len(payload)
    )


def load_pytree(
    file: Union[str, os.PathLike, IO],
    target_factory: Callable[[Optional[dict]], Any],
) -> Any:
    """Load a pytree artifact; ``target_factory(hyperparameters)`` rebuilds
    the target structure (typically the app's ``init``)."""
    from flax import serialization

    t0 = time.perf_counter()
    f, should_close = _open(file, "rb")
    try:
        magic = f.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError(
                f"not a unionml_tpu pytree artifact (bad magic {magic!r}); "
                "use a custom @model.loader for non-JAX artifacts"
            )
        (header_len,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(header_len))
        payload = f.read()
    finally:
        if should_close:
            f.close()
    target = target_factory(header.get("hyperparameters"))
    out = serialization.from_bytes(target, payload)
    metrics = checkpoint_metrics()
    metrics["restore_ms"].labels("pytree").observe(
        (time.perf_counter() - t0) * 1e3
    )
    metrics["restore_bytes"].labels("pytree").inc(len(payload))
    return out
