"""Checkpoint I/O telemetry, shared by pytree_io and sharded.

Every save/restore on either checkpoint path publishes
``unionml_checkpoint_{save,restore}_ms{kind}`` histograms (the wall
time the CALLER stalled — for the async :class:`CheckpointManager`
that is the wait-for-previous-commit plus launch, exactly the piece
that lands in the training loop's ``checkpoint`` badput bucket) and
``unionml_checkpoint_{save,restore}_bytes_total{kind}`` counters
(``kind="pytree"`` for the single-file msgpack artifact,
``kind="sharded"`` for Orbax). The series feed the goodput layer
(docs/observability.md "Training goodput") and give ROADMAP's
async-checkpoint work a before/after yardstick.
"""

from __future__ import annotations

from typing import Optional

from unionml_tpu import telemetry


def checkpoint_metrics(
    registry: Optional[telemetry.MetricsRegistry] = None,
) -> dict:
    """The four checkpoint I/O families on ``registry`` (default: the
    process-global one), keyed ``save_ms`` / ``restore_ms`` /
    ``save_bytes`` / ``restore_bytes``."""
    reg = registry if registry is not None else telemetry.get_registry()
    return {
        "save_ms": reg.histogram(
            "unionml_checkpoint_save_ms",
            "Caller-visible checkpoint save stall (async managers: wait "
            "for the previous commit + snapshot/launch).",
            ("kind",),
        ),
        "restore_ms": reg.histogram(
            "unionml_checkpoint_restore_ms",
            "Checkpoint restore wall time.",
            ("kind",),
        ),
        "save_bytes": reg.counter(
            "unionml_checkpoint_save_bytes_total",
            "Bytes written to checkpoints (pytree leaf bytes for "
            "sharded saves; serialized artifact bytes for pytree saves).",
            ("kind",),
        ),
        "restore_bytes": reg.counter(
            "unionml_checkpoint_restore_bytes_total",
            "Bytes restored from checkpoints.",
            ("kind",),
        ),
    }


def tree_nbytes(tree) -> int:
    """Total leaf bytes of a (possibly device-resident) pytree — the
    size a sharded save writes / a restore re-places. Leaves without
    ``nbytes`` (scalars, None) count 0; never raises."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:
        return 0
    total = 0
    for leaf in leaves:
        try:
            total += int(getattr(leaf, "nbytes", 0) or 0)
        except Exception:
            continue
    return total
