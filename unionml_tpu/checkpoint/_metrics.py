"""Checkpoint I/O telemetry, shared by pytree_io, sharded and async_writer.

Every save/restore on any checkpoint path publishes
``unionml_checkpoint_{save,restore}_ms{kind}`` histograms (the wall
time the CALLER stalled — for the async managers that is the
wait-for-previous-commit plus the device→host snapshot/launch, exactly
the piece that lands in the training loop's ``checkpoint`` badput
bucket) and ``unionml_checkpoint_{save,restore}_bytes_total{kind}``
counters (``kind="pytree"`` for the single-file msgpack artifact,
``kind="sharded"`` for Orbax, ``kind="async"`` for the background
commit writer). The async writer's background leg gets its own
series — ``unionml_checkpoint_commit_ms{kind}`` (serialize + write +
atomic rename, off the critical path) and the
``unionml_checkpoint_pending`` gauge (launched commits not yet
durable) — so save_ms can honestly shrink to the caller stall without
the disk cost disappearing from the scrape. The series feed the
goodput layer (docs/observability.md "Training goodput") and give the
overlapped-training work (docs/performance.md "Overlapped training")
its before/after yardstick.
"""

from __future__ import annotations

from typing import Optional

from unionml_tpu import telemetry


def checkpoint_metrics(
    registry: Optional[telemetry.MetricsRegistry] = None,
) -> dict:
    """The checkpoint I/O families on ``registry`` (default: the
    process-global one), keyed ``save_ms`` / ``restore_ms`` /
    ``save_bytes`` / ``restore_bytes`` / ``commit_ms`` / ``pending``."""
    reg = registry if registry is not None else telemetry.get_registry()
    return {
        "save_ms": reg.histogram(
            "unionml_checkpoint_save_ms",
            "Caller-visible checkpoint save stall (async managers: wait "
            "for the previous commit + device->host snapshot/launch; the "
            "background disk leg is unionml_checkpoint_commit_ms).",
            ("kind",),
        ),
        "commit_ms": reg.histogram(
            "unionml_checkpoint_commit_ms",
            "Background commit leg of an async save: serialize + write + "
            "atomic rename, overlapped with training steps.",
            ("kind",),
        ),
        "pending": reg.gauge(
            "unionml_checkpoint_pending",
            "Launched async checkpoint commits not yet durable (a crash "
            "now loses only these; the previous commit stays restorable).",
        ),
        "restore_ms": reg.histogram(
            "unionml_checkpoint_restore_ms",
            "Checkpoint restore wall time.",
            ("kind",),
        ),
        "save_bytes": reg.counter(
            "unionml_checkpoint_save_bytes_total",
            "Bytes written to checkpoints (pytree leaf bytes for "
            "sharded saves; serialized artifact bytes for pytree saves).",
            ("kind",),
        ),
        "restore_bytes": reg.counter(
            "unionml_checkpoint_restore_bytes_total",
            "Bytes restored from checkpoints.",
            ("kind",),
        ),
    }


def tree_nbytes(tree) -> int:
    """Total leaf bytes of a (possibly device-resident) pytree — the
    size a sharded save writes / a restore re-places. Leaves without
    ``nbytes`` (scalars, None) count 0; never raises."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:
        return 0
    total = 0
    for leaf in leaves:
        try:
            total += int(getattr(leaf, "nbytes", 0) or 0)
        except Exception:
            continue
    return total
