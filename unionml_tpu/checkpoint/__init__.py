"""Checkpoint/artifact layer: pytree serialization + sharded checkpoints.

The reference's artifact layer is joblib/torch.save per framework
(reference: unionml/model.py:931-988) with no mid-training checkpointing
(SURVEY.md §5.4). Here the JAX-native family gets:

- :func:`save_pytree` / :func:`load_pytree` — single-file msgpack artifact
  (flax serialization) for the Model.save/load path,
- :mod:`unionml_tpu.checkpoint.sharded` — Orbax sharded checkpoints of
  params + optimizer state for mid-training checkpoint/resume on a mesh,
- :mod:`unionml_tpu.checkpoint.async_writer` — framework-owned async
  checkpointing: ``save`` stalls the caller for the device→host
  snapshot only; the serialize/write/commit runs on a background
  thread with an atomic rename + commit marker, so a kill mid-commit
  always leaves the previous checkpoint restorable
  (:func:`make_checkpoint_manager` picks async vs. Orbax per process
  count and what's already on disk),
- :mod:`unionml_tpu.checkpoint.registry` — "registry = execution history"
  semantics (version = app git SHA × run id, ``latest``-or-pinned;
  reference: unionml/remote.py:150-218).
"""

from unionml_tpu.checkpoint.async_writer import (
    AsyncCheckpointManager,
    AsyncCheckpointWriter,
    make_checkpoint_manager,
)
from unionml_tpu.checkpoint.pytree_io import load_pytree, save_pytree
from unionml_tpu.checkpoint.sharded import CheckpointManager, restore_sharded, save_sharded

__all__ = [
    "save_pytree",
    "load_pytree",
    "save_sharded",
    "restore_sharded",
    "AsyncCheckpointManager",
    "AsyncCheckpointWriter",
    "CheckpointManager",
    "make_checkpoint_manager",
]
