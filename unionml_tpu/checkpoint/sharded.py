"""Sharded checkpoints for mid-training checkpoint/resume on a mesh.

No reference counterpart — the reference checkpoints only final artifacts
(SURVEY.md §5.3-5.4). TPU training needs preemption-safe, sharded
checkpoints: each host writes only its addressable shards (Orbax), and
restore re-places shards per the target's NamedSharding, enabling
deterministic resume from step N after slice preemption.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Optional, Union


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def save_sharded(path: Union[str, os.PathLike], state: Any, *, step: Optional[int] = None, force: bool = True) -> None:
    """Write a sharded checkpoint of ``state`` (params + opt state pytree)."""
    ocp = _ocp()
    path = Path(path).absolute()
    if step is not None:
        path = path / f"step_{step}"
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=force)


def restore_sharded(path: Union[str, os.PathLike], target: Any = None, *, step: Optional[int] = None) -> Any:
    """Restore a sharded checkpoint, re-placing shards to match ``target``'s
    shardings (abstract or concrete pytree)."""
    ocp = _ocp()
    path = Path(path).absolute()
    if step is not None:
        path = path / f"step_{step}"
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(path, target) if target is not None else ckptr.restore(path)


class CheckpointManager:
    """Step-indexed checkpoint rotation for training loops.

    Keeps the most recent ``max_to_keep`` step checkpoints under ``root``;
    ``latest_step()`` enables deterministic resume (SURVEY.md §5.3).
    """

    def __init__(self, root: Union[str, os.PathLike], *, max_to_keep: int = 3):
        self.root = Path(root).absolute()
        self.max_to_keep = max_to_keep
        self.root.mkdir(parents=True, exist_ok=True)

    def _steps(self):
        steps = []
        for p in self.root.glob("step_*"):
            try:
                steps.append(int(p.name.split("_", 1)[1]))
            except ValueError:
                continue
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self._steps()
        return steps[-1] if steps else None

    def save(self, step: int, state: Any) -> None:
        save_sharded(self.root, state, step=step)
        steps = self._steps()
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            import shutil

            shutil.rmtree(self.root / f"step_{victim}", ignore_errors=True)

    def restore(self, state_target: Any = None, step: Optional[int] = None) -> Any:
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.root}")
        return restore_sharded(self.root, state_target, step=step)
