"""Sharded checkpoints for mid-training checkpoint/resume on a mesh.

No reference counterpart — the reference checkpoints only final artifacts
(SURVEY.md §5.3-5.4). TPU training needs preemption-safe, sharded
checkpoints: each host writes only its addressable shards (Orbax), and
restore re-places shards per the target's NamedSharding, enabling
deterministic resume from step N after slice preemption.

Saves are **asynchronous by default** through a persistent
``StandardCheckpointer``: ``save`` snapshots device arrays to host, kicks
off the filesystem write in the background, and returns — the training
loop overlaps the write with the next steps. Orbax commits atomically
(tmp-dir rename), so a preemption mid-write never leaves a half
checkpoint: resume simply finds the previous complete step.
"""

from __future__ import annotations

import os
import shutil
import time
from pathlib import Path
from typing import Any, Optional, Union

from unionml_tpu.checkpoint._metrics import checkpoint_metrics, tree_nbytes


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def save_sharded(
    path: Union[str, os.PathLike],
    state: Any,
    *,
    step: Optional[int] = None,
    force: bool = True,
) -> None:
    """Write a sharded checkpoint of ``state`` (params + opt state pytree).

    Blocking one-shot form (artifact saves); training loops should use
    :class:`CheckpointManager` for overlapped async saves.
    """
    ocp = _ocp()
    path = Path(path).absolute()
    if step is not None:
        path = path / f"step_{step}"
    t0 = time.perf_counter()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=force)
    metrics = checkpoint_metrics()
    metrics["save_ms"].labels("sharded").observe(
        (time.perf_counter() - t0) * 1e3
    )
    metrics["save_bytes"].labels("sharded").inc(tree_nbytes(state))


def restore_sharded(path: Union[str, os.PathLike], target: Any = None, *, step: Optional[int] = None) -> Any:
    """Restore a sharded checkpoint, re-placing shards to match ``target``'s
    shardings (abstract or concrete pytree)."""
    ocp = _ocp()
    path = Path(path).absolute()
    if step is not None:
        path = path / f"step_{step}"
    t0 = time.perf_counter()
    with ocp.StandardCheckpointer() as ckptr:
        out = (
            ckptr.restore(path, target) if target is not None
            else ckptr.restore(path)
        )
    metrics = checkpoint_metrics()
    metrics["restore_ms"].labels("sharded").observe(
        (time.perf_counter() - t0) * 1e3
    )
    metrics["restore_bytes"].labels("sharded").inc(tree_nbytes(out))
    return out


class CheckpointManager:
    """Step-indexed checkpoint rotation for training loops.

    Keeps the most recent ``max_to_keep`` step checkpoints under ``root``
    (``0`` or ``None`` disables rotation and keeps every checkpoint);
    ``latest_step()`` enables deterministic resume (SURVEY.md §5.3).
    Pruning runs only after pending writes commit, so the number of
    *durable* checkpoints never drops below ``max_to_keep`` (one extra
    dir may exist transiently between a commit and the next prune).
    With ``async_save`` (default) each ``save`` waits for the previous
    write to commit (normally instant — it ran during the intervening
    training steps), then returns as soon as the new write is launched.
    Call :meth:`wait` (or ``close``) before reading the newest checkpoint
    back or ending the process.
    """

    def __init__(
        self,
        root: Union[str, os.PathLike],
        *,
        max_to_keep: int = 3,
        async_save: bool = True,
        registry: Optional[Any] = None,
    ):
        if max_to_keep is not None and max_to_keep < 0:
            raise ValueError(f"max_to_keep must be >= 0 or None, got {max_to_keep}")
        self.root = Path(root).absolute()
        self.max_to_keep = max_to_keep
        self.async_save = async_save
        self.root.mkdir(parents=True, exist_ok=True)
        self._ckptr = None
        # unionml_checkpoint_* save/restore histograms + bytes counters
        # (docs/observability.md): what save() observes is the CALLER
        # stall — for async saves the wait-for-previous-commit plus the
        # device->host snapshot/launch, i.e. the checkpoint badput the
        # training loop actually pays
        self._metrics = checkpoint_metrics(registry)

    def _checkpointer(self):
        if self._ckptr is None:
            self._ckptr = _ocp().StandardCheckpointer()
        return self._ckptr

    def _steps(self):
        steps = []
        for p in self.root.glob("step_*"):
            # in-flight async writes live in `step_N.orbax-checkpoint-tmp-*`
            # dirs: the int() parse skips them until commit renames
            try:
                steps.append(int(p.name.split("_", 1)[1]))
            except ValueError:
                continue
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self._steps()
        return steps[-1] if steps else None

    def _prune(self) -> None:
        # 0/None mean "keep everything" (without this, -0 makes the slice
        # [:None] and every committed checkpoint would be deleted)
        if not self.max_to_keep:
            return
        # only ever called right after wait_until_finished: every step dir
        # is committed, so deleting down to max_to_keep never drops the
        # durable count below max_to_keep even if the process dies now
        for victim in self._steps()[: -self.max_to_keep or None]:
            shutil.rmtree(self.root / f"step_{victim}", ignore_errors=True)

    def save(self, step: int, state: Any) -> None:
        t0 = time.perf_counter()
        ckptr = self._checkpointer()
        # one write in flight at a time: pruning must never race a pending
        # commit, and a second save would contend for host I/O
        ckptr.wait_until_finished()
        self._prune()
        ckptr.save(self.root / f"step_{step}", state, force=True)
        if not self.async_save:
            ckptr.wait_until_finished()
        self._metrics["save_ms"].labels("sharded").observe(
            (time.perf_counter() - t0) * 1e3
        )
        self._metrics["save_bytes"].labels("sharded").inc(tree_nbytes(state))

    def wait(self) -> None:
        """Block until every launched save has committed, then prune."""
        if self._ckptr is not None:
            self._ckptr.wait_until_finished()
            self._prune()

    def restore(self, state_target: Any = None, step: Optional[int] = None) -> Any:
        t0 = time.perf_counter()
        self.wait()
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.root}")
        ckptr = self._checkpointer()
        path = self.root / f"step_{step}"
        out = (
            ckptr.restore(path, state_target)
            if state_target is not None
            else ckptr.restore(path)
        )
        self._metrics["restore_ms"].labels("sharded").observe(
            (time.perf_counter() - t0) * 1e3
        )
        self._metrics["restore_bytes"].labels("sharded").inc(tree_nbytes(out))
        return out

    def close(self) -> None:
        if self._ckptr is not None:
            self.wait()
            self._ckptr.close()
            self._ckptr = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
