"""Async checkpointing: host snapshot now, durable commit in the background.

The Orbax path (:mod:`unionml_tpu.checkpoint.sharded`) already writes
asynchronously, but the training loop still pays a per-save stall that
the goodput layer attributes to the ``checkpoint`` badput bucket, and
the ``train_goodput`` attribution cannot see *inside* Orbax's worker.
This module is the framework-owned replacement for the single-process
case (CheckFreq / async-Orbax lineage): ``save`` snapshots the state
pytree to host memory — the device→host copy is the ONLY synchronous
cost — and a background thread serializes, writes, and **commits
atomically** (write into a ``*.tmp-*`` dir, fsync, drop a
``_COMMITTED`` marker, then ``os.replace`` onto the final name).
A kill at ANY point therefore leaves either the previous complete
checkpoint or the new complete checkpoint — never a torn one:

- crash before the rename → only an uncommitted ``*.tmp-*`` dir
  exists; :meth:`AsyncCheckpointManager.latest_step` ignores it and a
  restart resumes from the previous step (stale tmp dirs are swept on
  the next manager construction);
- a ``step_N`` dir missing its ``_COMMITTED`` marker (external
  interference, partial copy) is **refused** by restore and skipped by
  ``latest_step`` — a torn checkpoint can never be silently loaded.

Telemetry splits the two legs (docs/observability.md "Which metrics
each layer emits"): ``unionml_checkpoint_save_ms{kind="async"}``
records the caller stall (wait-for-previous-commit + snapshot +
launch), ``unionml_checkpoint_commit_ms{kind="async"}`` the background
serialize/write/rename, and the ``unionml_checkpoint_pending`` gauge
counts launched-but-not-yet-durable commits. A failed background
commit is logged, counted out of ``pending``, and re-raised on the
strict barrier (:meth:`~AsyncCheckpointWriter.wait`) — ``close`` is
best-effort cleanup and only logs, so a trainer's ``finally`` block
never masks the real exception with a checkpoint one.

Multi-process meshes keep the Orbax path (each host writes only its
addressable shards); :func:`make_checkpoint_manager` picks per
``jax.process_count()`` — and sticks with Orbax when ``root`` already
holds marker-less (Orbax-format) step dirs, so a resume never silently
restarts from scratch after a framework upgrade.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from pathlib import Path
from typing import Any, Callable, Optional, Union

from unionml_tpu._logging import logger
from unionml_tpu.checkpoint._metrics import checkpoint_metrics, tree_nbytes

__all__ = [
    "AsyncCheckpointManager",
    "AsyncCheckpointWriter",
    "COMMIT_MARKER",
    "is_committed",
    "make_checkpoint_manager",
]

#: Marker file a committed checkpoint dir must contain. Written inside
#: the tmp dir BEFORE the atomic rename, so a final-named dir without
#: it can only mean external interference — restore refuses it.
COMMIT_MARKER = "_COMMITTED"

_DATA_FILE = "state.msgpack"


def is_committed(path: Union[str, os.PathLike]) -> bool:
    """True iff ``path`` is a fully committed async checkpoint dir."""
    p = Path(path)
    return (p / COMMIT_MARKER).is_file() and (p / _DATA_FILE).is_file()


def _fsync_dir(path: Path) -> None:
    """fsync a DIRECTORY's entries: file-content fsyncs alone do not
    make creations/renames inside it durable across power loss."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _host_snapshot(state: Any) -> Any:
    """Device→host copy of every array leaf (the one synchronous cost
    of an async save). Forces any in-flight donated step to finish —
    after this returns, the training loop may freely donate/overwrite
    the device buffers."""
    import jax

    return jax.device_get(state)


def _replace_leaves(target: Any, restored: Any) -> Any:
    """Re-place restored host leaves per ``target``'s device placement:
    leaves that are jax.Arrays in the target keep their sharding
    (device_put of the host value), everything else stays host-side."""
    import jax

    def put(t, v):
        if isinstance(t, jax.Array):
            return jax.device_put(v, t.sharding)
        return v

    return jax.tree_util.tree_map(put, target, restored)


class AsyncCheckpointWriter:
    """One-at-a-time background committer for host-snapshotted pytrees.

    ``save(path, state)`` blocks only for (1) the previous commit —
    normally already durable, it ran during the intervening training
    steps — and (2) the device→host snapshot, then launches the
    serialize/write/rename on a daemon thread and returns. ``wait()``
    is the strict barrier: it blocks until the launched commit is
    durable and re-raises its failure, if any.

    ``commit_hook(final_path)`` is a test/chaos seam (the elastic
    trainer's ``fault_hook`` analog): it runs on the background thread
    just before the atomic rename, so a kill-mid-commit is an injected
    raise — the tmp dir stays uncommitted and the previous checkpoint
    remains the newest restorable one.
    """

    def __init__(
        self,
        *,
        registry: Optional[Any] = None,
        kind: str = "async",
        commit_hook: Optional[Callable[[Path], None]] = None,
    ):
        self.kind = kind
        self.commit_hook = commit_hook
        self._metrics = checkpoint_metrics(registry)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._pending = 0
        self._lock = threading.Lock()
        self._seq = 0

    # -- save --------------------------------------------------------------

    def save(
        self,
        path: Union[str, os.PathLike],
        state: Any,
        *,
        inline: bool = False,
    ) -> None:
        """Snapshot ``state`` to host and launch the background commit
        of ``path`` (a directory). Caller stall = wait-for-previous +
        snapshot + launch, observed as ``save_ms{kind}``. With
        ``inline=True`` the commit runs on the CALLER thread — the
        whole serialize/write/rename lands inside the ``save_ms``
        window, since that is genuinely what the caller stalled on (the
        overlap-off baseline); the failure, if any, surfaces on the
        next :meth:`wait`, same as the background form."""
        t0 = time.perf_counter()
        # one commit in flight at a time: a second writer would contend
        # for host I/O (and interleaved commits would reorder durability)
        self.wait()
        host_state = _host_snapshot(state)
        final = Path(path).absolute()
        self._seq += 1
        tmp = final.parent / f"{final.name}.tmp-{os.getpid()}-{self._seq}"
        with self._lock:
            self._pending += 1
            self._metrics["pending"].set(float(self._pending))
        if inline:
            self._commit(tmp, final, host_state)
        else:
            self._thread = threading.Thread(
                target=self._commit, args=(tmp, final, host_state),
                name=f"ckpt-commit-{final.name}", daemon=True,
            )
            self._thread.start()
        self._metrics["save_ms"].labels(self.kind).observe(
            (time.perf_counter() - t0) * 1e3
        )
        self._metrics["save_bytes"].labels(self.kind).inc(
            tree_nbytes(host_state)
        )

    def _commit(self, tmp: Path, final: Path, host_state: Any) -> None:
        t0 = time.perf_counter()
        try:
            from flax import serialization

            payload = serialization.to_bytes(host_state)
            tmp.mkdir(parents=True, exist_ok=True)
            data = tmp / _DATA_FILE
            with open(data, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            marker = tmp / COMMIT_MARKER
            with open(marker, "w") as f:
                json.dump({"nbytes": len(payload)}, f)
                f.flush()
                os.fsync(f.fileno())
            # directory entries need their own fsync for the durability
            # contract to survive power loss, not just process death:
            # the tmp dir's entries before the rename, the parent's
            # rename record after
            _fsync_dir(tmp)
            if self.commit_hook is not None:
                self.commit_hook(final)
            # the atomic point: a crash strictly before this line leaves
            # only the tmp dir (ignored by restore); after it, the final
            # dir is complete WITH its marker. Re-saving an existing
            # step (manual manager use, a rolled-back run re-reaching
            # the step number): os.replace cannot replace a non-empty
            # directory (ENOTEMPTY kills the commit), so the committed
            # dir is first moved aside onto the tmp namespace — restore
            # ignores *.tmp-* names, and a crash inside the two-rename
            # window loses only this step (latest_step falls back to
            # the previous committed one; the old behavior failed the
            # whole run instead)
            if final.is_dir():
                stale = final.parent / f"{final.name}.tmp-resave"
                shutil.rmtree(stale, ignore_errors=True)
                os.replace(final, stale)
                os.replace(tmp, final)
                shutil.rmtree(stale, ignore_errors=True)
            else:
                os.replace(tmp, final)
            _fsync_dir(final.parent)
            self._metrics["commit_ms"].labels(self.kind).observe(
                (time.perf_counter() - t0) * 1e3
            )
        except BaseException as exc:  # surfaces on the next wait()/save()
            with self._lock:
                self._error = exc
            shutil.rmtree(tmp, ignore_errors=True)
            logger.warning(
                f"async checkpoint commit of {final.name} failed: {exc!r}"
            )
        finally:
            with self._lock:
                self._pending -= 1
                self._metrics["pending"].set(float(self._pending))

    # -- barriers ----------------------------------------------------------

    def wait(self) -> None:
        """Block until the launched commit (if any) is durable;
        re-raises a background commit failure exactly once."""
        thread = self._thread
        if thread is not None:
            thread.join()
            self._thread = None
        with self._lock:
            error, self._error = self._error, None
        if error is not None:
            raise RuntimeError(
                "async checkpoint commit failed (the previous checkpoint "
                "is still the newest restorable one)"
            ) from error

    def close(self) -> None:
        """Best-effort drain: waits for the in-flight commit but only
        LOGS a failure — safe inside a trainer's ``finally`` where
        raising would mask the real exception."""
        try:
            self.wait()
        except RuntimeError as exc:
            logger.warning(f"async checkpoint writer closed dirty: {exc}")

    # -- restore -----------------------------------------------------------

    def restore(self, path: Union[str, os.PathLike], target: Any) -> Any:
        """Restore a committed checkpoint dir into ``target``'s
        structure and device placement. Refuses torn checkpoints: a dir
        without its commit marker raises instead of loading garbage."""
        t0 = time.perf_counter()
        self.wait()
        final = Path(path).absolute()
        if not final.is_dir():
            raise FileNotFoundError(f"no checkpoint at {final}")
        if not is_committed(final):
            raise ValueError(
                f"refusing torn checkpoint {final}: commit marker "
                f"{COMMIT_MARKER!r} missing (crash mid-write or partial "
                "copy) — restore an earlier committed step instead"
            )
        from flax import serialization

        payload = (final / _DATA_FILE).read_bytes()
        restored = serialization.from_bytes(target, payload)
        out = _replace_leaves(target, restored)
        self._metrics["restore_ms"].labels(self.kind).observe(
            (time.perf_counter() - t0) * 1e3
        )
        self._metrics["restore_bytes"].labels(self.kind).inc(len(payload))
        return out


class AsyncCheckpointManager:
    """Step-indexed checkpoint rotation over :class:`AsyncCheckpointWriter`.

    Same surface as the Orbax :class:`~unionml_tpu.checkpoint.sharded.
    CheckpointManager` (``save/restore/latest_step/wait/close``), so the
    elastic trainer swaps between them per
    :func:`make_checkpoint_manager`. Differences that matter:

    - ``save`` stalls the caller for the device→host snapshot only;
      the disk write overlaps the following training steps
      (``async_commit=False`` commits inline — the overlap-off
      baseline the ``train_overlap`` bench preset compares against);
    - ``latest_step``/``restore`` see only COMMITTED checkpoints, so a
      kill mid-commit resumes from the previous step instead of a torn
      dir (uncommitted ``*.tmp-*`` leftovers are swept at construction);
    - ``restore`` requires a ``state_target`` (the msgpack wire format
      needs the pytree structure to restore into).
    """

    def __init__(
        self,
        root: Union[str, os.PathLike],
        *,
        max_to_keep: int = 3,
        async_commit: bool = True,
        registry: Optional[Any] = None,
        commit_hook: Optional[Callable[[Path], None]] = None,
    ):
        if max_to_keep is not None and max_to_keep < 0:
            raise ValueError(
                f"max_to_keep must be >= 0 or None, got {max_to_keep}"
            )
        self.root = Path(root).absolute()
        self.max_to_keep = max_to_keep
        self.async_commit = async_commit
        self.root.mkdir(parents=True, exist_ok=True)
        self._writer = AsyncCheckpointWriter(
            registry=registry, commit_hook=commit_hook
        )
        # a crashed predecessor leaves *.tmp-* dirs: uncommitted garbage,
        # safe to sweep (the atomic rename means a commit either fully
        # happened or left only this)
        for stale in self.root.glob("step_*.tmp-*"):
            shutil.rmtree(stale, ignore_errors=True)
        # a directory holding ONLY marker-less step dirs is a different
        # format (an Orbax-era run): refusing beats what backend="sync"
        # / "async" forced here would otherwise do — see no committed
        # steps and silently restart the run from step 0 ("auto" detects
        # this and picks Orbax). A dir with at least one committed step
        # is ours: a stray marker-less dir there is a torn external copy,
        # skipped per the restore contract.
        markerless = [
            p.name for p in self.root.glob("step_*")
            if p.is_dir() and "tmp" not in p.name and not is_committed(p)
        ]
        if markerless and not self._steps():
            raise ValueError(
                f"{self.root} holds checkpoint dirs without commit "
                f"markers ({sorted(markerless)[:3]}…): an Orbax-format "
                "run this manager cannot restore — resuming here would "
                "silently restart from step 0. Use backend='orbax' (or "
                "'auto') for this directory."
            )

    def _steps(self):
        steps = []
        for p in self.root.glob("step_*"):
            try:
                step = int(p.name.split("_", 1)[1])
            except ValueError:
                continue  # in-flight *.tmp-* dirs and strangers
            if is_committed(p):
                steps.append(step)
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        """Newest COMMITTED step (torn/in-flight dirs never count)."""
        steps = self._steps()
        return steps[-1] if steps else None

    def _prune(self) -> None:
        if not self.max_to_keep:
            return  # 0/None keep everything
        # only called after wait(): every counted dir is committed, so
        # the durable count never drops below max_to_keep
        for victim in self._steps()[: -self.max_to_keep or None]:
            shutil.rmtree(self.root / f"step_{victim}", ignore_errors=True)

    def save(self, step: int, state: Any) -> None:
        """Launch the commit of ``step``; caller pays snapshot only
        (plus the wait for the previous commit, normally already done —
        the writer waits INSIDE its timed window, so ``save_ms`` records
        the whole documented stall). Pruning needs no barrier: it only
        ever removes COMMITTED dirs, never an in-flight rename target.
        With ``async_commit=False`` the commit runs inline on the
        caller thread — the full serialize/write/rename stall lands in
        ``save_ms``, which is exactly what the caller paid."""
        self._prune()
        self._writer.save(
            self.root / f"step_{step}", state,
            inline=not self.async_commit,
        )
        if not self.async_commit:
            self._writer.wait()  # surfaces the inline commit's failure

    def wait(self) -> None:
        """Strict barrier: block until every launched save is durable
        (re-raising background failures), then prune."""
        self._writer.wait()
        self._prune()

    def restore(self, state_target: Any = None, step: Optional[int] = None) -> Any:
        if state_target is None:
            raise ValueError(
                "AsyncCheckpointManager.restore needs a state_target: the "
                "msgpack wire format restores INTO a pytree structure "
                "(pass the freshly-initialized state)"
            )
        self._writer.close()  # drain, but let restore pick the survivor
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no committed checkpoints under {self.root}")
        return self._writer.restore(self.root / f"step_{step}", state_target)

    def close(self) -> None:
        """Best-effort drain + prune (logs, never raises — safe in
        ``finally`` blocks)."""
        self._writer.close()
        self._prune()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def make_checkpoint_manager(
    root: Union[str, os.PathLike],
    *,
    max_to_keep: int = 3,
    backend: str = "auto",
    async_commit: bool = True,
    registry: Optional[Any] = None,
):
    """The checkpoint-manager factory the trainer loops use.

    ``backend="auto"`` picks :class:`AsyncCheckpointManager`
    single-process and the Orbax
    :class:`~unionml_tpu.checkpoint.sharded.CheckpointManager` under
    ``jax.process_count() > 1`` (each host must write only its
    addressable shards) — and falls back to Orbax when ``root``
    already holds marker-less (Orbax-format) step dirs, so resuming an
    existing run never silently restarts from step 0. ``"async"`` /
    ``"orbax"`` force a side; ``"sync"`` (or ``async_commit=False``)
    is the async manager with INLINE commits — the caller pays
    serialize+write+rename, the overlap-off baseline the
    ``train_overlap`` bench preset measures against.
    """
    if backend not in ("auto", "async", "orbax", "sync"):
        raise ValueError(
            f"unknown checkpoint backend {backend!r}: "
            "expected 'auto', 'async', 'orbax' or 'sync'"
        )
    if backend == "sync":
        backend, async_commit = "async", False
    if backend == "auto":
        import jax

        backend = "orbax" if jax.process_count() > 1 else "async"
        if backend == "async":
            for p in Path(root).absolute().glob("step_*"):
                if "tmp" in p.name or not p.is_dir():
                    continue
                if not is_committed(p):
                    # pre-existing Orbax-format checkpoints: stay Orbax
                    backend = "orbax"
                    break
    if backend == "async":
        return AsyncCheckpointManager(
            root, max_to_keep=max_to_keep, async_commit=async_commit,
            registry=registry,
        )
    from unionml_tpu.checkpoint.sharded import CheckpointManager

    return CheckpointManager(
        root, max_to_keep=max_to_keep, async_save=async_commit,
        registry=registry,
    )
