"""Default resources applied to every generated stage.

Reference: unionml/defaults.py:5 (``DEFAULT_RESOURCES = Resources(cpu="1",
mem="1Gi")``), where Resources constrain the Flyte task container. The
TPU-native resource model adds an accelerator request: ``chips`` is the
number of TPU chips a stage asks for (0 = host-only stage).

Resources are CONSUMED at launch (not decorative): both remote backends
derive the runner's environment from the executed workflow's resource
maxima via :func:`resources_env` — a ``chips=0`` workflow runs with
``JAX_PLATFORMS=cpu`` (a host-only stage never grabs the accelerator a
co-tenant serving process is using), and ``cpu`` caps the host math
threadpools. ``mem`` is advisory on TPU VMs (no container boundary to
enforce it; it documents the expected host footprint and is recorded in
the deploy manifest for schedulers that can act on it).
"""

from dataclasses import dataclass
from typing import Dict, Optional


@dataclass(frozen=True)
class Resources:
    """Resource request attached to a compiled stage."""

    cpu: str = "1"
    mem: str = "1Gi"
    chips: int = 0
    accelerator: Optional[str] = None  # e.g. "tpu-v5e", "tpu-v5p"


def cpu_count(resources: "Resources") -> int:
    """Parse the k8s-style cpu request to a whole host-thread count
    (fractional requests round UP: "500m" → 1, "1500m" → 2)."""
    import math

    raw = str(resources.cpu).strip()
    try:
        value = float(raw[:-1]) / 1000.0 if raw.endswith("m") else float(raw)
    except ValueError:
        return 1
    return max(1, math.ceil(value))


def resources_env(resources: "Resources") -> Dict[str, str]:
    """Launch-environment derivation — the consumer that makes a
    resource request real on a TPU VM (reference parity anchor:
    unionml/defaults.py:5, where Resources size the task container):

    - ``chips == 0`` → ``JAX_PLATFORMS=cpu``: host-only workflows (data
      prep, registry ops) must not initialize the TPU runtime and evict
      a serving process's HBM;
    - ``cpu`` → ``OMP_NUM_THREADS`` / ``OPENBLAS_NUM_THREADS`` host
      threadpool caps (the 1-core TPU VM failure mode is oversubscribed
      BLAS threads stalling the input pipeline).
    """
    env = {
        "OMP_NUM_THREADS": str(cpu_count(resources)),
        "OPENBLAS_NUM_THREADS": str(cpu_count(resources)),
    }
    if resources.chips == 0:
        env["JAX_PLATFORMS"] = "cpu"
    return env


DEFAULT_RESOURCES = Resources(cpu="1", mem="1Gi", chips=0)
DEFAULT_DEVICE_RESOURCES = Resources(cpu="4", mem="8Gi", chips=1, accelerator="tpu-v5e")
