"""Default resources applied to every generated stage.

Reference: unionml/defaults.py:5 (``DEFAULT_RESOURCES = Resources(cpu="1",
mem="1Gi")``). The TPU-native resource model adds an accelerator request:
``chips`` is the number of TPU chips a stage asks for (0 = host-only stage).
"""

from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class Resources:
    """Resource request attached to a compiled stage."""

    cpu: str = "1"
    mem: str = "1Gi"
    chips: int = 0
    accelerator: Optional[str] = None  # e.g. "tpu-v5e", "tpu-v5p"


DEFAULT_RESOURCES = Resources(cpu="1", mem="1Gi", chips=0)
DEFAULT_DEVICE_RESOURCES = Resources(cpu="4", mem="8Gi", chips=1, accelerator="tpu-v5e")
