"""Execution-side entrypoint: rehydrate the app and run one workflow.

This is the analog of the flytekit container entrypoint + the reference's
task resolver (reference: task_resolver.py:16-31): the runner re-imports
the deployed app module, finds the Model variable, regenerates its
compiled stages, and executes the requested workflow with the recorded
inputs. On multi-host TPU slices it first brings up ``jax.distributed``
from the coordinator env set by :class:`TPUVMBackend`.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import pickle
import sys
import traceback
from pathlib import Path


def _set_status(exec_dir: Path, status: str):
    # atomic replace: the backend's wait() polls this file concurrently
    record_path = exec_dir / "record.json"
    record = json.loads(record_path.read_text())
    record["status"] = status
    tmp = record_path.with_suffix(".json.tmp")
    tmp.write_text(json.dumps(record))
    os.replace(tmp, record_path)


def _load_model_artifact(model, exec_dir: Path, model_version: str):
    """Resolve a model version (execution id or 'latest') from the registry
    and load its artifact into ``model`` (reference: model.py:872-894)."""
    from unionml_tpu.remote.backend import LocalBackend

    backend = LocalBackend(
        project=os.environ.get("UNIONML_TPU_PROJECT", model.name.replace("_", "-")),
        root=os.environ.get("UNIONML_TPU_HOME"),
    )
    record = backend.get_model_execution(model, model_version=model_version)
    outputs = backend.fetch_outputs(record)
    from unionml_tpu.model import ModelArtifact
    from unionml_tpu.remote.artifacts import decode_model_object

    model.artifact = ModelArtifact(
        decode_model_object(model, outputs["model_object"]),
        outputs.get("hyperparameters"),
        outputs.get("metrics"),
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--app", required=True, help="module:variable of the Model")
    parser.add_argument("--workflow", required=True,
                        choices=["train", "predict", "predict_from_features"])
    parser.add_argument("--exec-dir", required=True)
    parser.add_argument("--model-version", default="latest")
    args = parser.parse_args(argv)

    exec_dir = Path(args.exec_dir)
    _set_status(exec_dir, "RUNNING")
    try:
        # multi-host bring-up when the TPU VM backend set coordinator env
        if "JAX_COORDINATOR_ADDRESS" in os.environ:
            from unionml_tpu.parallel import multihost_initialize

            multihost_initialize(
                coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
                num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
                process_id=int(os.environ["JAX_PROCESS_ID"]),
            )

        sys.path.insert(0, os.getcwd())
        module_name, var_name = args.app.split(":")
        module = importlib.import_module(module_name)
        model = getattr(module, var_name)

        with open(exec_dir / "inputs.pkl", "rb") as f:
            inputs = pickle.load(f)

        if args.workflow == "train":
            trainer_kwargs = inputs.pop("trainer_kwargs", None) or {}
            model_object, metrics = model.train(
                hyperparameters=inputs.pop("hyperparameters", None),
                loader_kwargs=inputs.pop("loader_kwargs", None),
                splitter_kwargs=inputs.pop("splitter_kwargs", None),
                parser_kwargs=inputs.pop("parser_kwargs", None),
                trainer_kwargs=trainer_kwargs,
                **inputs,
            )
            outputs = {
                "model_object": model.artifact.model_object,
                "hyperparameters": model.artifact.hyperparameters,
                "metrics": metrics,
            }
        else:
            _load_model_artifact(model, exec_dir, args.model_version)
            features = inputs.pop("features", None)
            predictions = model.predict(features=features, **inputs)
            outputs = {"predictions": predictions}

        # only process 0 writes outputs on multi-host runs
        process_id = int(os.environ.get("JAX_PROCESS_ID", "0"))
        if process_id == 0:
            from unionml_tpu.remote.artifacts import dump_outputs

            with open(exec_dir / "outputs.pkl", "wb") as f:
                # JAX train states aren't picklable (optax closures):
                # dump falls back to the app's saver bytes
                dump_outputs(model, outputs, f)
            _set_status(exec_dir, "SUCCEEDED")
        return 0
    except Exception:
        traceback.print_exc()
        _set_status(exec_dir, "FAILED")
        return 1


if __name__ == "__main__":
    sys.exit(main())
