"""Execution backends: local subprocess sandbox + TPU VM slices over SSH.

The reference delegates remote execution to a Flyte cluster (admin gRPC +
containers; reference remote.py:111-147, model.py:732-917). Here:

- :class:`LocalBackend` runs each workflow in a **separate process** with
  cwd set to the versioned deployment directory — a faithful analog of the
  container boundary, and the single-node sandbox the test suite uses the
  way the reference uses ``flytectl sandbox`` (reference:
  tests/integration/test_flyte_remote.py:33-57).
- :class:`TPUVMBackend` drives TPU VM slices over SSH: source is pushed to
  every worker, the runner is launched on all hosts with the
  ``jax.distributed`` coordinator env, and host 0's outputs are fetched
  back. This is the control plane standing in for Flyte admin
  (SURVEY.md §7 layer 7).

Both share the registry layout::

    {root}/deployments/{project}/{domain}/{app_version}/   # packaged source
    {root}/executions/{project}/{execution_id}/            # inputs/outputs/status/logs
"""

from __future__ import annotations

import json
import os
import pickle
import re
import subprocess
import sys
import time
import uuid
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Any, Dict, List, Optional

from unionml_tpu._logging import logger
from unionml_tpu.defaults import Resources, cpu_count, resources_env


def _workflow_resources(workflow) -> Resources:
    """The launch-time resource envelope of a workflow: the max over its
    stages (one launcher process hosts the whole DAG, so it must satisfy
    the hungriest stage)."""
    reqs = [node.stage.resources for node in workflow.nodes]
    if not reqs:
        return Resources()
    hungriest = max(reqs, key=lambda r: r.chips)
    return Resources(
        cpu=str(max(cpu_count(r) for r in reqs)),
        mem=max((r.mem for r in reqs), key=_mem_bytes),
        chips=hungriest.chips,
        # the accelerator TYPE must come from the stage that asked for
        # the most chips — pairing max-chips with another stage's type
        # would provision the wrong hardware; if the hungriest stage
        # left it unset, record None honestly rather than guess
        accelerator=hungriest.accelerator,
    )


def _mem_bytes(mem: str) -> int:
    """Parse k8s-style memory ("1Gi", "512Mi", "2G") for comparison."""
    units = {
        "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50,
        "K": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15,
    }
    raw = str(mem).strip()
    for suffix, mult in sorted(units.items(), key=lambda kv: -len(kv[0])):
        if raw.endswith(suffix):
            try:
                return int(float(raw[: -len(suffix)]) * mult)
            except ValueError:
                return 0
    try:
        return int(float(raw))
    except ValueError:
        return 0


def _model_resources_table(model) -> Dict[str, Dict[str, Any]]:
    """Per-workflow resource records for the deploy manifest. Workflows
    that cannot build yet (a trainer-only app has no predictor) are
    simply absent — deploy must not demand more of the model than
    execution will (the pre-round-4 behavior recorded names only)."""
    table: Dict[str, Dict[str, Any]] = {}
    for build in (
        model.train_workflow,
        model.predict_workflow,
        model.predict_from_features_workflow,
    ):
        try:
            wf = build()
        except ValueError:
            # the registration guards ("has no predictor/trainer") — a
            # trainer-only app legitimately lacks predict workflows.
            # Anything else (a real dataset/model bug) must fail the
            # deploy, not silently drop the workflow's resource record.
            continue
        table[wf.name] = asdict(_workflow_resources(wf))
    return table


def _manifest_env(manifest: Dict[str, Any], workflow: str) -> Dict[str, str]:
    """Runner env derived from the deployed manifest's resource record
    (absent on pre-round-4 manifests → no overrides, old behavior)."""
    table = manifest.get("resources") or {}
    rec = table.get(workflow)
    if rec is None:
        # executions may name workflows by their short form ("train")
        # while the manifest records "<model>.train"
        rec = next(
            (r for name, r in table.items() if name.endswith(f".{workflow}")),
            None,
        )
    if not rec:
        return {}
    return resources_env(Resources(**rec))

DEFAULT_ROOT_ENV = "UNIONML_TPU_HOME"
DEFAULT_ROOT = "~/.unionml_tpu"


@dataclass
class ExecutionRecord:
    """One workflow execution (the FlyteWorkflowExecution analog)."""

    execution_id: str
    project: str
    workflow: str
    app_version: str
    status: str = "QUEUED"  # QUEUED | RUNNING | SUCCEEDED | FAILED
    created_at: float = field(default_factory=time.time)
    exec_dir: str = ""
    console_url: str = ""
    # "" = app-reported failure (deterministic — never worth relaunching);
    # "preempted" = the runner died without reporting (set by the
    # dead-runner detector) — the only failure max_restarts retries
    failure_kind: str = ""

    def save(self):
        # atomic write: wait() polls this file from another process
        path = Path(self.exec_dir) / "record.json"
        tmp = path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(asdict(self)))
        os.replace(tmp, path)

    @classmethod
    def load(cls, exec_dir) -> "ExecutionRecord":
        data = json.loads((Path(exec_dir) / "record.json").read_text())
        return cls(**data)


class BaseBackend:
    def __init__(self, *, project: str, domain: str = "development", root: Optional[str] = None):
        self.project = project
        self.domain = domain
        self.root = Path(
            root or os.environ.get(DEFAULT_ROOT_ENV, DEFAULT_ROOT)
        ).expanduser()

    # ---------- layout ----------

    def deployment_dir(self, app_version: str) -> Path:
        return self.root / "deployments" / self.project / self.domain / app_version

    def executions_dir(self) -> Path:
        return self.root / "executions" / self.project

    def _latest_app_version(self) -> str:
        base = self.root / "deployments" / self.project / self.domain
        if not base.exists():
            raise FileNotFoundError(
                f"no deployments for project {self.project!r}; run remote_deploy first"
            )
        versions = sorted(base.iterdir(), key=lambda p: p.stat().st_mtime)
        return versions[-1].name

    # ---------- deploy ----------

    def deploy(self, model, *, app_version: str, patch: bool = False) -> Path:
        """Package the app source (reference deploy_wf: remote.py:111-147).

        The app source dir is the directory containing the module where the
        Model was defined; the manifest records the ``module:variable``
        loader path (the task-resolver pointer, task_resolver.py:23-31).
        """
        from unionml_tpu.remote.packaging import package_source

        module_name, var_name = model.loader_path()
        module = sys.modules[module_name]
        module_file = getattr(module, "__file__", None)
        if module_file is None:
            raise ValueError(
                f"cannot deploy: app module {module_name!r} has no file (interactive?)"
            )
        src_dir = Path(module_file).parent
        dest = self.deployment_dir(app_version)
        n = package_source(src_dir, dest, patch=patch)
        manifest = {
            "app": f"{Path(module_file).stem}:{var_name}",
            "model_name": model.name,
            "app_version": app_version,
            "project": self.project,
            "domain": self.domain,
            "workflows": [
                model.train_workflow_name,
                model.predict_workflow_name,
                model.predict_from_features_workflow_name,
            ],
            # per-workflow resource maxima (reference parity:
            # unionml/defaults.py:5 sizes the task container; here the
            # launcher derives the runner env from these — defaults.py
            # resources_env)
            "resources": _model_resources_table(model),
        }
        (dest / ".unionml_manifest.json").write_text(json.dumps(manifest, indent=2))
        logger.info(f"deployed {n} files to {dest}")
        return dest

    # ---------- execute ----------

    def execute(
        self,
        model,
        *,
        workflow: str,
        app_version: Optional[str] = None,
        model_version: Optional[str] = None,
        inputs: Optional[Dict[str, Any]] = None,
        wait: bool = True,
        max_restarts: int = 0,
    ) -> ExecutionRecord:
        """``max_restarts``: preemption recovery (SURVEY §5.3) — when the
        runner dies (slice preemption, OOM-kill, spot eviction) the SAME
        execution relaunches up to this many times. With the train step
        registered as ``@model.train_step(checkpoint_dir=...)`` each
        relaunch resumes from the newest checkpoint, reaching the
        bit-identical state of an uninterrupted run instead of training
        from scratch (the reference delegates this retry loop to Flyte;
        reference: tests/integration/test_flyte_remote.py:72-79 is its
        only in-repo trace). Requires ``wait=True``."""
        if max_restarts and not wait:
            raise ValueError(
                "max_restarts needs wait=True (the relaunch loop watches "
                "the execution to completion)"
            )
        app_version = app_version or self._latest_app_version()
        dep_dir = self.deployment_dir(app_version)
        if not dep_dir.exists():
            raise FileNotFoundError(
                f"app version {app_version!r} is not deployed (looked in {dep_dir})"
            )
        manifest = json.loads((dep_dir / ".unionml_manifest.json").read_text())

        execution_id = f"{workflow}-{uuid.uuid4().hex[:10]}"
        exec_dir = self.executions_dir() / execution_id
        exec_dir.mkdir(parents=True, exist_ok=True)
        with open(exec_dir / "inputs.pkl", "wb") as f:
            pickle.dump(inputs or {}, f)

        record = ExecutionRecord(
            execution_id=execution_id,
            project=self.project,
            workflow=workflow,
            app_version=app_version,
            exec_dir=str(exec_dir),
            console_url=f"file://{exec_dir}",
        )
        record.save()
        self._launch(record, dep_dir, manifest, model_version=model_version)
        # surface the console URL (reference: model.py:785-789)
        logger.info(f"execution {execution_id}: {record.console_url}")
        if wait:
            attempt = 0
            while True:
                try:
                    return self.wait(record)
                except RuntimeError:
                    # relaunch ONLY genuine preemptions (runner died
                    # without reporting): an app-reported FAILED is
                    # deterministic — retrying it just repeats the crash
                    try:
                        kind = ExecutionRecord.load(record.exec_dir).failure_kind
                    except (OSError, json.JSONDecodeError, TypeError):
                        kind = ""
                    if attempt >= max_restarts or kind != "preempted":
                        raise
                    attempt += 1
                    logger.info(
                        f"execution {execution_id} died; relaunching "
                        f"(attempt {attempt}/{max_restarts}) — a "
                        "checkpoint_dir train step resumes from its "
                        "newest checkpoint"
                    )
                    # reset the FAILED record BEFORE relaunching, or the
                    # next wait() reads the stale terminal status and
                    # raises before the runner sets RUNNING
                    record = ExecutionRecord.load(record.exec_dir)
                    record.status = "QUEUED"
                    record.failure_kind = ""
                    record.save()
                    self._launch(
                        record, dep_dir, manifest, model_version=model_version
                    )
        return record

    def _launch(self, record, dep_dir, manifest, *, model_version):  # pragma: no cover
        raise NotImplementedError

    # ---------- status / outputs ----------

    def wait(self, execution: ExecutionRecord, timeout: float = 3600.0, poll: float = 0.2) -> ExecutionRecord:
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                execution = ExecutionRecord.load(execution.exec_dir)
            except (json.JSONDecodeError, FileNotFoundError):
                time.sleep(poll)  # mid-write record; retry
                continue
            if execution.status in ("SUCCEEDED", "FAILED"):
                if execution.status == "FAILED":
                    log = Path(execution.exec_dir) / "runner.log"
                    tail = log.read_text()[-2000:] if log.exists() else "<no log>"
                    raise RuntimeError(
                        f"execution {execution.execution_id} FAILED. Log tail:\n{tail}"
                    )
                return execution
            time.sleep(poll)
        raise TimeoutError(f"execution {execution.execution_id} did not finish in {timeout}s")

    def fetch_outputs(self, execution: ExecutionRecord) -> Dict[str, Any]:
        with open(Path(execution.exec_dir) / "outputs.pkl", "rb") as f:
            return pickle.load(f)

    # ---------- registry = execution history (reference: remote.py:150-218) ----

    def _train_executions(self, model, app_version: Optional[str]) -> List[ExecutionRecord]:
        base = self.executions_dir()
        if not base.exists():
            return []
        records = []
        for d in base.iterdir():
            try:
                rec = ExecutionRecord.load(d)
            except (FileNotFoundError, json.JSONDecodeError):
                continue
            if rec.workflow != "train" or rec.status != "SUCCEEDED":
                continue
            if app_version is not None and rec.app_version != app_version:
                continue
            records.append(rec)
        return sorted(records, key=lambda r: r.created_at, reverse=True)

    def get_model_execution(
        self, model, *, app_version: Optional[str] = None, model_version: str = "latest"
    ) -> ExecutionRecord:
        """latest-or-pinned model version (reference: remote.py:150-183)."""
        if model_version != "latest":
            exec_dir = self.executions_dir() / model_version
            record = ExecutionRecord.load(exec_dir)
            if record.workflow != "train" or record.status != "SUCCEEDED":
                raise ValueError(
                    f"model_version {model_version!r} is not a SUCCEEDED train "
                    f"execution (workflow={record.workflow!r}, status={record.status!r})"
                )
            return record
        records = self._train_executions(model, app_version)
        if not records:
            raise FileNotFoundError(
                f"no successful train executions for project {self.project!r}"
                + (f" app_version {app_version!r}" if app_version else "")
            )
        return records[0]

    def list_model_versions(self, model, *, app_version=None, limit: int = 10) -> List[str]:
        """Model versions = succeeded train execution ids
        (reference: remote.py:197-218)."""
        return [r.execution_id for r in self._train_executions(model, app_version)[:limit]]


def _runner_dead(pid: int) -> bool:
    """True when the runner process is gone OR a zombie.

    The launcher never blocks on its Popen, so a hard-killed runner
    lingers as a ZOMBIE in this process — and zombies still accept
    ``os.kill(pid, 0)``, which is exactly how the naive liveness probe
    missed the death (found by the preemption e2e hanging). Reap our own
    children with ``waitpid(WNOHANG)``; for runners launched by another
    process (rehydrated backend), probe with signal 0 plus a /proc
    zombie-state check."""
    try:
        done, _status = os.waitpid(pid, os.WNOHANG)
        return done == pid
    except ChildProcessError:
        pass  # not our child: fall through to the probe
    except OSError:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return True
    except PermissionError:
        return False
    try:
        with open(f"/proc/{pid}/stat") as f:
            # field 3 (after the parenthesized comm) is the state
            return f.read().rsplit(")", 1)[1].split()[0] == "Z"
    except OSError:
        return False


class LocalBackend(BaseBackend):
    """Subprocess sandbox: the single-node stand-in for a real backend."""

    def _launch(self, record, dep_dir, manifest, *, model_version):
        cmd = [
            sys.executable,
            "-m",
            "unionml_tpu.remote.runner",
            "--app", manifest["app"],
            "--workflow", record.workflow,
            "--exec-dir", record.exec_dir,
        ]
        if model_version:
            cmd += ["--model-version", model_version]
        env = dict(os.environ)
        # the deployed source + the framework itself must be importable
        fw_root = str(Path(__file__).resolve().parents[2])
        env["PYTHONPATH"] = os.pathsep.join(
            [str(dep_dir), fw_root, env.get("PYTHONPATH", "")]
        )
        env["UNIONML_TPU_HOME"] = str(self.root)
        env["UNIONML_TPU_PROJECT"] = self.project
        res_env = _manifest_env(manifest, record.workflow)
        if res_env:
            env.update(res_env)
            logger.info(
                f"resources applied to {record.workflow}: {res_env}"
            )
        # append: a max_restarts relaunch must not destroy the previous
        # attempt's log (the preemption evidence an operator debugs with)
        log = open(Path(record.exec_dir) / "runner.log", "a")
        proc = subprocess.Popen(cmd, cwd=dep_dir, env=env, stdout=log, stderr=log)
        (Path(record.exec_dir) / "pid").write_text(str(proc.pid))

    def wait(self, execution: ExecutionRecord, timeout: float = 3600.0, poll: float = 0.2) -> ExecutionRecord:
        """Base wait + DEAD-RUNNER detection: a hard-killed runner
        (preemption, OOM-kill, ``kill -9``) never writes a terminal
        status, so the record stays RUNNING forever. Here a non-terminal
        record whose pid is gone is marked FAILED — which is what lets
        ``execute(..., max_restarts=N)`` relaunch it (the §5.3
        preemption-recovery loop) instead of hanging to timeout."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                execution = ExecutionRecord.load(execution.exec_dir)
            except (json.JSONDecodeError, FileNotFoundError):
                time.sleep(poll)
                continue
            if execution.status in ("SUCCEEDED", "FAILED"):
                return super().wait(execution, timeout=poll * 2, poll=poll)
            pid_path = Path(execution.exec_dir) / "pid"
            # QUEUED counts too: a runner that dies before reaching its
            # RUNNING write (bad interpreter, import crash, instant
            # preemption) must fail the execution, not hang it
            if execution.status in ("QUEUED", "RUNNING") and pid_path.exists():
                try:
                    pid = int(pid_path.read_text())
                except ValueError:
                    pid = None
                if pid is not None and _runner_dead(pid):
                    # grace re-read: the runner may have just written its
                    # terminal status before exiting
                    execution = ExecutionRecord.load(execution.exec_dir)
                    if execution.status not in ("SUCCEEDED", "FAILED"):
                        log = Path(execution.exec_dir) / "runner.log"
                        with open(log, "a") as f:
                            f.write(
                                f"\nrunner pid {pid} died without "
                                "reporting a terminal status (preempted?)\n"
                            )
                        execution.status = "FAILED"
                        execution.failure_kind = "preempted"
                        execution.save()
                        continue
            time.sleep(poll)
        raise TimeoutError(
            f"execution {execution.execution_id} did not finish in {timeout}s"
        )


class TPUVMBackend(BaseBackend):
    """SSH control plane for TPU VM slices (multi-host).

    Config (from the backend YAML): ``hosts`` (worker addresses, host 0 is
    the coordinator), ``ssh_user``, ``workdir``, ``shared_fs`` (whether the
    exec dir is visible on every host — NFS/GCS-fuse; when False, inputs
    are scp'd out and host 0's outputs scp'd back), ``provision`` (build
    the framework wheel + pinned requirements and pip-install them on
    every host at deploy time — the ``docker_build_push`` analog,
    reference remote.py:69-108). Source is pushed to every worker; the
    runner launches on all hosts with ``jax.distributed.initialize``
    coordinator env so XLA collectives span the slice (SURVEY.md §5.8).

    ``_launch`` keeps every SSH process; :meth:`wait` joins them all and
    aggregates per-host failures (rc + log tail) instead of silently
    returning — a host-1 crash fails the execution, like a lost Flyte pod
    fails the workflow.
    """

    def __init__(self, *, hosts: List[str], ssh_user: str = "root",
                 workdir: str = "/tmp/unionml_tpu_app", coordinator_port: int = 8476,
                 shared_fs: bool = True, provision: bool = True,
                 image: Optional[str] = None, image_push: bool = True,
                 dockerfile: Optional[str] = None, **kwargs):
        """``image``: optional container repository (e.g.
        ``gcr.io/proj/unionml-tpu``). When set, full deploys build the
        framework ``Dockerfile`` tagged ``{image}:{app_version}``, push
        it (unless ``image_push: false`` — e.g. a registry mirrored to
        the hosts), and pull it on every host; executions then run the
        runner INSIDE the container (workdir and registry bind-mounted)
        so the remote environment is an immutable per-version artifact —
        the reference's ``docker_build_push`` mode (remote.py:69-108).
        Patch deploys skip the build/pull, mirroring fast registration.
        ``dockerfile`` overrides the default (the framework root's).
        """
        super().__init__(**kwargs)
        if not hosts:
            raise ValueError("TPUVMBackend requires at least one host")
        self.hosts = hosts
        self.ssh_user = ssh_user
        self.workdir = workdir
        self.coordinator_port = coordinator_port
        self.shared_fs = shared_fs
        self.provision = provision
        self.image = image
        self.image_push = image_push
        self.dockerfile = dockerfile
        # execution_id -> {"procs": [(host, Popen, logfile)], "targets": [...]}
        self._procs: Dict[str, Dict[str, Any]] = {}
        # (host, app_version) pairs already pushed by THIS process: execute()
        # after deploy() skips re-pushing the identical tree (incl. wheels)
        self._pushed: set = set()

    # ---------- transport primitives (monkeypatch points for tests) ----------

    def _ssh(self, host: str, command: str, **popen_kwargs):
        """Streaming remote command (non-blocking Popen)."""
        return subprocess.Popen(
            ["ssh", "-o", "StrictHostKeyChecking=no", f"{self.ssh_user}@{host}", command],
            **popen_kwargs,
        )

    def _run_ssh(self, host: str, command: str) -> subprocess.CompletedProcess:
        """Blocking remote command with captured output."""
        return subprocess.run(
            ["ssh", "-o", "StrictHostKeyChecking=no", f"{self.ssh_user}@{host}", command],
            capture_output=True, text=True,
        )

    def _run_ssh_checked(self, host: str, command: str):
        """Blocking remote command; raises with stderr on failure."""
        proc = self._run_ssh(host, command)
        if proc.returncode != 0:
            raise RuntimeError(
                f"remote command failed on {host} (rc={proc.returncode}): "
                f"{command}\n{(proc.stderr or '').strip()[-500:]}"
            )
        return proc

    def _scp_to(self, host: str, src: str, dst: str):
        subprocess.run(
            ["scp", "-r", "-q", "-o", "StrictHostKeyChecking=no", src,
             f"{self.ssh_user}@{host}:{dst}"],
            check=True,
        )

    def _scp_from(self, host: str, src: str, dst: str):
        subprocess.run(
            ["scp", "-r", "-q", "-o", "StrictHostKeyChecking=no",
             f"{self.ssh_user}@{host}:{src}", dst],
            check=True,
        )

    def _run_docker(self, args: List[str]) -> subprocess.CompletedProcess:
        """Local docker invocation (build/push run on the deploying
        machine; hosts only pull). Monkeypatch point for tests."""
        return subprocess.run(["docker"] + args, capture_output=True, text=True)

    # ---------- image mode (docker_build_push analog) ----------

    def _image_tag(self, app_version: str) -> str:
        # patch deploys skip the image build and run in the BASE
        # version's container — fast registration semantics: source
        # changes ride the scp push, the environment is pinned. Only a
        # TRAILING "-patch<hex>" (the exact suffix deploy() appends) is
        # stripped; user versions that merely contain "-patch" keep
        # their own tag.
        base = re.sub(r"-patch[0-9a-f]+$", "", app_version)
        return f"{self.image}:{base}"

    def _build_and_distribute_image(self, app_version: str) -> str:
        """Build the framework image for this version, push it, and pull
        it on every host. The image pins the ENVIRONMENT; app source
        still rides the scp push (so patch redeploys stay seconds)."""
        tag = self._image_tag(app_version)
        fw_root = Path(__file__).resolve().parents[2]
        dockerfile = self.dockerfile or str(fw_root / "Dockerfile")
        if not Path(dockerfile).exists():
            # a pip-installed package has no Dockerfile next to it — the
            # default only works from a source checkout
            raise RuntimeError(
                f"image mode needs a Dockerfile: {dockerfile} does not "
                "exist (the framework appears to be installed as a "
                "package, not a source checkout). Set `dockerfile:` in "
                "the backend config to your build file."
            )
        context = str(Path(dockerfile).parent)
        proc = self._run_docker(
            ["build", "-t", tag, "-f", dockerfile, context]
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"docker build failed for {tag}:\n{(proc.stderr or '')[-800:]}"
            )
        if self.image_push:
            proc = self._run_docker(["push", tag])
            if proc.returncode != 0:
                raise RuntimeError(
                    f"docker push failed for {tag}:\n{(proc.stderr or '')[-800:]}"
                )
        from concurrent.futures import ThreadPoolExecutor

        def pull_host(host: str) -> Optional[str]:
            pull = self._run_ssh(host, f"docker pull {tag}")
            if pull.returncode != 0:
                return f"{host}: {(pull.stderr or '').strip()[-300:]}"
            return None

        # hosts pull independently: multi-GB images at max(host), not
        # sum(hosts), on big slices (same reason as pip provisioning)
        with ThreadPoolExecutor(max_workers=min(16, len(self.hosts))) as pool:
            errors = [e for e in pool.map(pull_host, self.hosts) if e]
        if errors:
            raise RuntimeError(
                f"docker pull failed on {len(errors)}/{len(self.hosts)} "
                "hosts:\n" + "\n".join(errors)
            )
        logger.info(f"image {tag} built and distributed to {len(self.hosts)} hosts")
        return tag

    # ---------- deploy + environment provisioning ----------

    def deploy(self, model, *, app_version: str, patch: bool = False) -> Path:
        """Package source; build + install the pinned environment.

        Full deploys build an environment bundle (framework wheel +
        ``requirements.lock`` pinned to the versions running here) and
        pip-install it on every host, so the remote env is reproducible —
        the reference's image build/push (remote.py:69-108). Patch deploys
        skip provisioning, mirroring fast registration (remote.py:126-138).
        """
        dest = super().deploy(model, app_version=app_version, patch=patch)
        # a re-deploy of the same version string (e.g. a second '-dirty'
        # deploy after edits) must re-push: drop its push-dedup entries
        self._pushed = {p for p in self._pushed if p[1] != app_version}
        if self.image:
            # image mode supersedes pip provisioning: the environment is
            # the container, built once per version
            if not patch:
                self._build_and_distribute_image(app_version)
            return dest
        if self.provision and not patch:
            from concurrent.futures import ThreadPoolExecutor

            from unionml_tpu.remote import packaging

            packaging.build_environment_bundle(dest)

            def provision_host(host: str) -> Optional[str]:
                target = self._push(host, dest, app_version)
                proc = self._run_ssh(
                    host,
                    f"python -m pip install -q -r {target}/_env/requirements.lock "
                    f"--no-index --find-links {target}/_env && "
                    f"python -m pip install -q --no-deps --force-reinstall "
                    f"{target}/_env/*.whl || "
                    # no local wheel cache for the pinned deps (fresh VM with
                    # network): fall back to a plain pinned install
                    f"(python -m pip install -q -r {target}/_env/requirements.lock && "
                    f"python -m pip install -q --no-deps --force-reinstall "
                    f"{target}/_env/*.whl)",
                )
                if proc.returncode != 0:
                    return f"{host}: {proc.stderr.strip()[-500:]}"
                return None

            # hosts are independent: provision concurrently so deploy time
            # is max(host), not sum(hosts), on big slices
            with ThreadPoolExecutor(max_workers=min(16, len(self.hosts))) as pool:
                errors = [e for e in pool.map(provision_host, self.hosts) if e]
            if errors:
                raise RuntimeError(
                    "environment provisioning failed on "
                    f"{len(errors)}/{len(self.hosts)} hosts:\n" + "\n".join(errors)
                )
            logger.info(
                f"provisioned pinned environment on {len(self.hosts)} hosts"
            )
        return dest

    def _push(self, host: str, src: Path, app_version: str) -> str:
        """Push the deployment to a per-version dir so repeated deploys never
        nest inside (or silently reuse) a previous version's workdir.

        Idempotent within one process: a version already pushed to a host
        (e.g. by deploy(), or a previous execute()) is not re-transferred.
        """
        target = f"{self.workdir}/{app_version}"
        if (host, app_version) in self._pushed:
            return target
        self._run_ssh_checked(host, f"rm -rf {target} && mkdir -p {target}")
        self._scp_to(host, f"{src}/.", target)
        self._pushed.add((host, app_version))
        return target

    # ---------- launch / wait ----------

    def _stage_model_registry(self, model_version):
        """Copy the resolved train execution to every host's local registry.

        Without a shared filesystem the hosts cannot see this machine's
        execution history, so predict workflows could never resolve a
        trained model: stage the one SUCCEEDED train execution the runner
        will ask for (latest or pinned) into ``{root}/executions`` on each
        host — the runner's ``_load_model_artifact`` then finds it through
        the same registry layout it uses locally. The staged record's
        ``exec_dir`` is rewritten to the HOST-side path first: the
        deployer-local path inside record.json would send the runner's
        ``fetch_outputs`` to a directory that doesn't exist over there.
        """
        import shutil
        import tempfile

        src = self.get_model_execution(None, model_version=model_version or "latest")
        remote_dir = f"{self.root}/executions/{self.project}/{src.execution_id}"
        with tempfile.TemporaryDirectory(prefix="unionml_tpu_stage_") as tmp:
            stage = Path(tmp) / src.execution_id
            shutil.copytree(src.exec_dir, stage)
            data = json.loads((stage / "record.json").read_text())
            data["exec_dir"] = remote_dir
            (stage / "record.json").write_text(json.dumps(data))
            for host in self.hosts:
                self._run_ssh_checked(host, f"mkdir -p {remote_dir}")
                self._scp_to(host, f"{stage}/.", remote_dir)

    def _launch(self, record, dep_dir, manifest, *, model_version):
        targets = [self._push(host, dep_dir, record.app_version) for host in self.hosts]
        coordinator = f"{self.hosts[0]}:{self.coordinator_port}"
        if not self.shared_fs and record.workflow != "train":
            self._stage_model_registry(model_version)
        procs = []
        for i, host in enumerate(self.hosts):
            if self.shared_fs:
                remote_exec = record.exec_dir
            else:
                # private filesystems: stage inputs+record into a
                # per-execution dir in the pushed workdir; host 0's copy is
                # fetched back in wait()
                remote_exec = f"{targets[i]}/_exec/{record.execution_id}"
                self._run_ssh_checked(host, f"mkdir -p {remote_exec}")
                self._scp_to(host, f"{record.exec_dir}/.", remote_exec)
            env = {
                "UNIONML_TPU_HOME": str(self.root),
                "UNIONML_TPU_PROJECT": self.project,
            }
            res_env = _manifest_env(manifest, record.workflow)
            if res_env:
                env.update(res_env)
                logger.info(
                    f"resources applied to {record.workflow} on {host}: "
                    f"{res_env}"
                )
            if len(self.hosts) > 1:
                # single-host VMs skip jax.distributed entirely
                env.update({
                    "JAX_COORDINATOR_ADDRESS": coordinator,
                    "JAX_NUM_PROCESSES": str(len(self.hosts)),
                    "JAX_PROCESS_ID": str(i),
                })
            runner = (
                f"python -m unionml_tpu.remote.runner --app {manifest['app']} "
                f"--workflow {record.workflow} --exec-dir {remote_exec}"
                + (f" --model-version {model_version}" if model_version else "")
            )
            if self.image:
                # run the runner inside the per-version container: host
                # networking for the jax.distributed coordinator,
                # --privileged for TPU device access, workdir + registry
                # bind-mounted so pushes/records work exactly as uncontained
                env_flags = " ".join(f"-e {k}={v}" for k, v in env.items())
                cmd = (
                    f"docker run --rm --privileged --network host "
                    f"-v {targets[i]}:{targets[i]} -v {self.root}:{self.root} "
                    f"-w {targets[i]} {env_flags} "
                    f"{self._image_tag(record.app_version)} {runner}"
                )
            else:
                env_prefix = " ".join(f"{k}={v}" for k, v in env.items())
                cmd = f"cd {targets[i]} && {env_prefix} {runner}"
            log_path = Path(record.exec_dir) / f"runner.host{i}.log"
            log = open(log_path, "w")
            procs.append((host, self._ssh(host, cmd, stdout=log, stderr=log), log))
        self._procs[record.execution_id] = {"procs": procs, "targets": targets}

    def wait(self, execution: ExecutionRecord, timeout: float = 3600.0, poll: float = 0.2) -> ExecutionRecord:
        """Join every host's SSH process, aggregate failures, fetch outputs.

        Unlike the base class (which only polls the record file), a dead
        or non-zero host process fails the execution with that host's rc
        and log tail — per-host failures propagate instead of hanging the
        poll loop until timeout.
        """
        launched = self._procs.pop(execution.execution_id, None)
        if launched is None:
            # not launched by this process: record polling. With
            # shared_fs: false the local record only turns terminal when
            # the LAUNCHING process's wait() scp's it back — a re-wait
            # after that fetch, or a monitor process on the launcher's
            # machine, still succeeds; on timeout, append the likely cause
            # (keeping the TimeoutError type so retry loops still work)
            try:
                return super().wait(execution, timeout=timeout, poll=poll)
            except TimeoutError as e:
                if not self.shared_fs:
                    raise TimeoutError(
                        f"{e} — note: this backend has shared_fs: false and "
                        "this process did not launch the execution, so the "
                        "local record only updates when the launching "
                        "process's wait() fetches it back. If the launcher "
                        "is gone, this wait can never succeed; call wait() "
                        "from the process that called execute(), or enable "
                        "shared_fs."
                    ) from e
                raise
        deadline = time.time() + timeout
        failures = []
        # poll ALL hosts concurrently: a crashed worker is detected
        # immediately even while host 0 blocks in a collective waiting for
        # the dead peer — the survivors are then killed rather than letting
        # them hang until the deadline
        pending = {i: hp for i, hp in enumerate(launched["procs"])}
        while pending and time.time() < deadline and not failures:
            for i in sorted(pending):
                host, proc, log = pending[i]
                rc = proc.poll()
                if rc is None:
                    continue
                del pending[i]
                log.close()
                if rc != 0:
                    failures.append((i, host, f"rc={rc}"))
            if pending and not failures:
                time.sleep(poll)
        for i in sorted(pending):  # first failure or deadline: reap survivors
            host, proc, log = pending[i]
            proc.kill()
            proc.wait()
            log.close()
            why = ("killed after another host failed" if failures
                   else f"timeout after {timeout}s")
            failures.append((i, host, why))
        if not self.shared_fs and not failures:
            # host 0 holds the authoritative record + outputs
            self._scp_from(
                self.hosts[0],
                f"{launched['targets'][0]}/_exec/{execution.execution_id}/.",
                execution.exec_dir,
            )
        if failures:
            detail = []
            for i, host, why in failures:
                log_path = Path(execution.exec_dir) / f"runner.host{i}.log"
                tail = log_path.read_text()[-1000:] if log_path.exists() else "<no log>"
                detail.append(f"host {i} ({host}): {why}\n{tail}")
            if not self.shared_fs:
                # best-effort record fetch from EVERY failing host (an
                # app crash may be reported by a non-coordinator host —
                # fetching only host 0 would misclassify it), falling
                # back to host 0 for the survivor-kill case
                for i in sorted({i for i, _, _ in failures} | {0}):
                    try:
                        self._scp_from(
                            self.hosts[i],
                            f"{launched['targets'][i]}/_exec/"
                            f"{execution.execution_id}/record.json",
                            execution.exec_dir,
                        )
                    except Exception:  # pragma: no cover - transport
                        continue
                    try:
                        if ExecutionRecord.load(execution.exec_dir).status == "FAILED":
                            break
                    except (OSError, json.JSONDecodeError, TypeError):
                        continue
            # classify for the max_restarts loop: a runner that wrote its
            # own FAILED status crashed deterministically (never worth
            # relaunching); a host PROCESS that died without reporting
            # (slice preemption, eviction, OOM-kill) is retryable. A pure
            # wall-clock timeout is NEITHER — the remote runners may
            # still be alive, and relaunching over them would race two
            # coordinators on the same ports/exec dir.
            try:
                reported = (
                    ExecutionRecord.load(execution.exec_dir).status == "FAILED"
                )
            except (OSError, json.JSONDecodeError, TypeError):
                reported = False
            host_died = any(why.startswith("rc=") for _, _, why in failures)
            execution.status = "FAILED"
            if host_died and not reported:
                execution.failure_kind = "preempted"
            execution.save()
            raise RuntimeError(
                f"execution {execution.execution_id} FAILED on "
                f"{len(failures)}/{len(self.hosts)} hosts:\n" + "\n".join(detail)
            )
        return super().wait(execution, timeout=max(1.0, deadline - time.time()), poll=poll)


def get_backend(
    config_file: Optional[str] = None,
    *,
    project: str,
    domain: str = "development",
) -> BaseBackend:
    """Build a backend from YAML config, defaulting to the local sandbox
    (the reference's Config.auto localhost fallback, model.py:661-663)."""
    if config_file:
        import yaml

        with open(config_file) as f:
            cfg = yaml.safe_load(f) or {}
        backend_cfg = cfg.get("backend", {})
        if backend_cfg.get("type") == "tpu_vm":
            return TPUVMBackend(
                hosts=backend_cfg["hosts"],
                ssh_user=backend_cfg.get("ssh_user", "root"),
                workdir=backend_cfg.get("workdir", "/tmp/unionml_tpu_app"),
                coordinator_port=backend_cfg.get("coordinator_port", 8476),
                shared_fs=backend_cfg.get("shared_fs", True),
                provision=backend_cfg.get("provision", True),
                project=project,
                domain=domain,
                root=backend_cfg.get("root"),
            )
        return LocalBackend(project=project, domain=domain, root=backend_cfg.get("root"))
    return LocalBackend(project=project, domain=domain)
