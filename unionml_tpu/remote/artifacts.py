"""Model-object transport across the execution boundary.

The backend moves workflow outputs as a pickle (the reference moves them
through Flyte's object store with a FlytePickle fallback —
reference: model.py:884-894, __init__.py:26-28). JAX training states are
NOT picklable: the optax ``GradientTransformation`` inside a TrainState
closes over local functions. When direct pickling fails, the model
object rides as the app's own saved-artifact bytes (``Model._saver`` —
msgpack for pytrees, pytree_io.py) and is rehydrated on the consuming
side with ``Model._loader``, which rebuilds the structure through the
app's ``init`` exactly like ``Model.load`` does.
"""

from __future__ import annotations

import io
import pickle
from typing import Any

SAVED_KEY = "__unionml_tpu_saved_artifact__"


def encode_model_object(model, model_object: Any, hyperparameters: Any = None) -> Any:
    """Saved-artifact stand-in for an unpicklable ``model_object``."""
    buf = io.BytesIO()
    model._saver(model_object, hyperparameters, buf)
    return {SAVED_KEY: buf.getvalue()}


def dump_outputs(model, outputs: dict, file) -> None:
    """Pickle workflow outputs, falling back to saver-encoded model bytes.

    The success path serializes exactly once (no throwaway picklability
    probe of a possibly multi-hundred-MB object); only when the whole
    outputs dict fails to pickle is the model object re-encoded through
    the app's saver and the dump retried.
    """
    try:
        blob = pickle.dumps(outputs)
    except Exception as e:
        # name the actual offender before retrying: the saver fallback only
        # helps when model_object is what failed — if some other key is
        # unpicklable the retry would fail again with a second traceback
        # masking the original cause. Probe the cheap keys FIRST: serializing
        # a possibly multi-hundred-MB model_object is pointless whenever any
        # other key is already known bad.
        bad = []
        for k, v in sorted(outputs.items(), key=lambda kv: kv[0] == "model_object"):
            if bad and k == "model_object":
                break  # another offender already decides the outcome
            try:
                pickle.dumps(v)
            except Exception:
                bad.append(k)
        if bad != ["model_object"]:
            # bad == []: the failure isn't attributable to any single value
            # (unpicklable dict key, cross-value cycle) — re-encoding the
            # model object can't help and would misdirect the diagnosis
            raise RuntimeError(
                "workflow outputs are not picklable: "
                + (
                    f"offending key(s) {bad}; only 'model_object' has a "
                    "saver-encoded fallback"
                    if bad
                    else "no single value is at fault (every value pickles "
                    "alone) — likely an unpicklable key or a cycle spanning "
                    "values"
                )
            ) from e
        outputs = {
            **outputs,
            "model_object": encode_model_object(
                model, outputs.get("model_object"), outputs.get("hyperparameters")
            ),
        }
        try:
            blob = pickle.dumps(outputs)
        except Exception as e2:
            raise RuntimeError(
                "model_object could not be pickled directly and its "
                "saver-encoded fallback also failed to pickle"
            ) from e2
    file.write(blob)


def decode_model_object(model, obj: Any) -> Any:
    """Inverse of :func:`encode_model_object` on the consuming side."""
    if isinstance(obj, dict) and SAVED_KEY in obj:
        return model._loader(io.BytesIO(obj[SAVED_KEY]))
    return obj
