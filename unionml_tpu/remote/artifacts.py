"""Model-object transport across the execution boundary.

The backend moves workflow outputs as a pickle (the reference moves them
through Flyte's object store with a FlytePickle fallback —
reference: model.py:884-894, __init__.py:26-28). JAX training states are
NOT picklable: the optax ``GradientTransformation`` inside a TrainState
closes over local functions. When direct pickling fails, the model
object rides as the app's own saved-artifact bytes (``Model._saver`` —
msgpack for pytrees, pytree_io.py) and is rehydrated on the consuming
side with ``Model._loader``, which rebuilds the structure through the
app's ``init`` exactly like ``Model.load`` does.
"""

from __future__ import annotations

import io
import pickle
from typing import Any

SAVED_KEY = "__unionml_tpu_saved_artifact__"


def encode_model_object(model, model_object: Any, hyperparameters: Any = None) -> Any:
    """Saved-artifact stand-in for an unpicklable ``model_object``."""
    buf = io.BytesIO()
    model._saver(model_object, hyperparameters, buf)
    return {SAVED_KEY: buf.getvalue()}


def dump_outputs(model, outputs: dict, file) -> None:
    """Pickle workflow outputs, falling back to saver-encoded model bytes.

    The success path serializes exactly once (no throwaway picklability
    probe of a possibly multi-hundred-MB object); only when the whole
    outputs dict fails to pickle is the model object re-encoded through
    the app's saver and the dump retried.
    """
    try:
        blob = pickle.dumps(outputs)
    except Exception:
        outputs = {
            **outputs,
            "model_object": encode_model_object(
                model, outputs.get("model_object"), outputs.get("hyperparameters")
            ),
        }
        blob = pickle.dumps(outputs)
    file.write(blob)


def decode_model_object(model, obj: Any) -> Any:
    """Inverse of :func:`encode_model_object` on the consuming side."""
    if isinstance(obj, dict) and SAVED_KEY in obj:
        return model._loader(io.BytesIO(obj[SAVED_KEY]))
    return obj
