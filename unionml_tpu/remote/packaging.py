"""App versioning + source packaging.

Reference behavior being replicated:
- ``get_app_version`` (remote.py:43-57): version = git HEAD SHA; raises
  :class:`VersionFetchError` on a dirty tree unless ``allow_uncommitted``.
- fast/patch registration (remote.py:126-138): package source only,
  skipping the expensive image build — here the "image" is the full
  deployment copy and a patch overlays source files onto an existing
  deployment.

git is invoked via subprocess (no gitpython dependency).
"""

from __future__ import annotations

import shutil
import subprocess
import uuid
from pathlib import Path
from typing import Iterable, Optional

EXCLUDE_DIRS = {".git", "__pycache__", ".pytest_cache", ".unionml_tpu", ".cache", "node_modules"}


class VersionFetchError(RuntimeError):
    """Raised when an app version cannot be derived (reference: remote.py:24)."""


def _git(args, cwd=None) -> str:
    out = subprocess.run(
        ["git", *args], cwd=cwd, capture_output=True, text=True, check=True
    )
    return out.stdout.strip()


def get_app_version(allow_uncommitted: bool = False, cwd: Optional[str] = None) -> str:
    """Git-SHA app version with dirty-tree guard (reference: remote.py:43-57)."""
    try:
        dirty = _git(["status", "--porcelain"], cwd=cwd)
        if dirty and not allow_uncommitted:
            raise VersionFetchError(
                "Git working tree has uncommitted changes; commit them or pass "
                "allow_uncommitted=True to version the app anyway."
            )
        sha = _git(["rev-parse", "HEAD"], cwd=cwd)
        return sha[:7] if not dirty else f"{sha[:7]}-dirty"
    except subprocess.CalledProcessError as exc:
        raise VersionFetchError(
            f"Could not derive app version from git: {exc.stderr or exc}"
        ) from exc
    except FileNotFoundError as exc:
        raise VersionFetchError("git binary not found") from exc


def patch_suffix() -> str:
    """Short unique suffix for patch versions (reference: model.py:700-701)."""
    return uuid.uuid4().hex[:8]


def iter_source_files(src: Path) -> Iterable[Path]:
    for path in sorted(src.rglob("*")):
        rel = path.relative_to(src)
        if any(part in EXCLUDE_DIRS for part in rel.parts):
            continue
        if path.is_file():
            yield path


def framework_root() -> Path:
    """Repo root of the installed-from-source framework (has pyproject.toml)."""
    return Path(__file__).resolve().parents[2]


def _parse_dependencies_toml(text: str) -> list:
    """The ``[project] dependencies = [...]`` array as a list of spec
    strings, parsed textually for hosts without :mod:`tomllib`
    (Python < 3.11). Handles the shape this repo's pyproject.toml
    uses — one bracketed array of quoted strings with optional ``#``
    comments — including specs that themselves contain brackets
    (``"jax[tpu]>=0.4"``): the closing ``]`` only terminates the array
    when scanned OUTSIDE a quoted string."""
    import re

    in_project = False
    buf = None
    done = False
    for line in text.splitlines():
        stripped = line.strip()
        if buf is None:
            if stripped.startswith("["):
                in_project = stripped == "[project]"
                continue
            match = (
                re.match(r"dependencies\s*=\s*\[", stripped)
                if in_project else None
            )
            if match is None:
                continue
            buf = ""
            stripped = stripped[match.end():]
        # append up to the first closing bracket outside quotes
        quote = None
        for i, ch in enumerate(stripped):
            if quote is not None:
                if ch == quote:
                    quote = None
            elif ch in "\"'":
                quote = ch
            elif ch == "#":        # comment: rest of line ignored
                stripped = stripped[:i]
                break
            elif ch == "]":
                stripped = stripped[:i]
                done = True
                break
        buf += stripped
        if done:
            break
    if buf is None:
        raise KeyError("dependencies")
    return [
        a or b for a, b in re.findall(r'"([^"]+)"|\'([^\']+)\'', buf)
    ]


def pinned_requirements() -> str:
    """``name==version`` lines for the framework's runtime dependencies.

    The pins come from the versions importable HERE, so a provisioned host
    reproduces the deploying machine's environment — the role the
    reference's docker image plays (reference: remote.py:69-108). Deps
    that aren't installed locally fall back to the unpinned spec.
    """
    import re
    from importlib import metadata

    try:
        import tomllib  # Python >= 3.11
    except ModuleNotFoundError:
        tomllib = None
    try:
        pyproject = framework_root() / "pyproject.toml"
        if tomllib is not None:
            with open(pyproject, "rb") as f:
                specs = tomllib.load(f)["project"]["dependencies"]
        else:
            # Python 3.10 hosts (TPU VM images still ship it): extract
            # the [project] dependencies array textually — the narrow
            # subset of TOML this file actually uses — instead of
            # shipping an unpinned environment
            specs = _parse_dependencies_toml(pyproject.read_text())
    except (FileNotFoundError, KeyError):
        specs = []
    lines = []
    for spec in specs:
        name = re.split(r"[><=!~\[;]", spec, maxsplit=1)[0].strip()
        try:
            lines.append(f"{name}=={metadata.version(name)}")
        except metadata.PackageNotFoundError:
            lines.append(spec)
    return "\n".join(lines) + "\n"


def build_environment_bundle(dest_dir) -> Path:
    """Build the deployable environment under ``{dest}/_env``.

    Contents: the framework wheel (built offline via ``pip wheel
    --no-deps --no-build-isolation``) and ``requirements.lock`` (pinned
    runtime deps). :class:`~unionml_tpu.remote.backend.TPUVMBackend`
    pip-installs the bundle on every host at deploy time — the analog of
    the reference's image build+push (remote.py:69-108) without a
    container registry in the loop.
    """
    import subprocess
    import sys
    import tempfile

    env_dir = Path(dest_dir) / "_env"
    env_dir.mkdir(parents=True, exist_ok=True)
    root = framework_root()
    if not (root / "pyproject.toml").exists():
        raise RuntimeError(
            "environment provisioning requires a source checkout of "
            f"unionml_tpu (no pyproject.toml at {root}); for a pip-installed "
            "framework, pre-provision the hosts and set provision: false in "
            "the backend config"
        )
    with tempfile.TemporaryDirectory(prefix="unionml_tpu_wheel_") as tmp:
        # build from a minimal copy: setuptools writes build/ + *.egg-info
        # into the source dir, which would dirty the git tree and trip the
        # get_app_version dirty-tree guard on the next deploy
        stage = Path(tmp) / "src"
        stage.mkdir()
        for name in ("pyproject.toml", "README.md"):
            if (root / name).exists():
                shutil.copy2(root / name, stage / name)
        shutil.copytree(
            root / "unionml_tpu", stage / "unionml_tpu",
            ignore=shutil.ignore_patterns("__pycache__", "*.pyc", "*.so"),
        )
        proc = subprocess.run(
            [sys.executable, "-m", "pip", "wheel", "--no-deps",
             "--no-build-isolation", "-w", str(env_dir), str(stage)],
            capture_output=True, text=True,
        )
    if proc.returncode != 0:
        raise RuntimeError(f"framework wheel build failed:\n{proc.stderr[-1000:]}")
    (env_dir / "requirements.lock").write_text(pinned_requirements())
    return env_dir


def package_source(src_dir, dest_dir, *, patch: bool = False) -> int:
    """Copy the app source tree into a deployment directory.

    Full mode replaces ``dest_dir``; patch mode overlays files onto the
    existing deployment (the fast-registration analog,
    reference remote.py:126-138). Returns the number of files packaged.
    """
    src, dest = Path(src_dir), Path(dest_dir)
    if not patch and dest.exists():
        shutil.rmtree(dest)
    dest.mkdir(parents=True, exist_ok=True)
    count = 0
    for f in iter_source_files(src):
        target = dest / f.relative_to(src)
        target.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy2(f, target)
        count += 1
    return count
