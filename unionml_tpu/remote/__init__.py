"""Remote backend: app packaging, versioned deploys, executions, registry.

Capability parity with reference unionml/remote.py:24-218 without Flyte:

- **App versioning** from git SHA with a dirty-tree guard
  (:func:`get_app_version`; reference remote.py:43-57), patch versions for
  fast source-only redeploys (reference remote.py:126-138).
- **Deploy** = package the app source into a versioned deployment directory
  (the container-image analog; reference remote.py:69-147).
- **Execute** = run a workflow in a separate process (the container
  boundary) that **rehydrates** the app by re-importing the app module and
  regenerating its stages — the reference's task-resolver trick
  (reference task_resolver.py:16-31).
- **Registry = execution history**: a model version is a SUCCEEDED train
  execution id; ``latest``-or-pinned fetch (reference remote.py:150-218).

Backends: :class:`~unionml_tpu.remote.backend.LocalBackend` (subprocess
sandbox, the flytectl-sandbox analog used by tests) and
:class:`~unionml_tpu.remote.backend.TPUVMBackend` (SSH control plane to TPU
VM slices with ``jax.distributed`` multi-host bring-up).
"""

from unionml_tpu.remote.backend import (
    ExecutionRecord,
    LocalBackend,
    TPUVMBackend,
    get_backend,
)
from unionml_tpu.remote.packaging import (
    VersionFetchError,
    build_environment_bundle,
    get_app_version,
    package_source,
    patch_suffix,
    pinned_requirements,
)


def get_model(app: str, reload: bool = False):
    """Load a Model from an ``"module:variable"`` string
    (reference: remote.py:28-33)."""
    import importlib

    module_name, var = app.split(":")
    module = importlib.import_module(module_name)
    if reload:
        importlib.reload(module)
    return getattr(module, var)


def load_latest_artifact(model, app_version=None, model_version: str = "latest"):
    """Fetch a model artifact from the execution registry into
    ``model.artifact`` (reference: remote.py:186-194 + model.py:872-894)."""
    backend = model._remote
    execution = backend.get_model_execution(
        model, app_version=app_version, model_version=model_version
    )
    return model.remote_load(execution)


__all__ = [
    "ExecutionRecord",
    "LocalBackend",
    "TPUVMBackend",
    "get_backend",
    "VersionFetchError",
    "build_environment_bundle",
    "pinned_requirements",
    "get_app_version",
    "package_source",
    "patch_suffix",
    "get_model",
    "load_latest_artifact",
]
