"""Sequence-parallel Llama training: the full step under shard_map.

Long-context training (SURVEY.md §5.7): activations — not parameters —
are the memory bottleneck, so the sequence dimension shards over the
mesh's ``sequence`` axis and attention runs sequence-sharded: a ring
(``attn_impl="ring"`` or the Pallas-local ``"ring_flash"``,
ops/ring_attention.py — ppermute KV rotation) or ``"ulysses"``
(ops/ulysses.py — an all_to_all head<->sequence reshuffle each way).
Everything else in the decoder is position-local (embedding, RMSNorm,
MLP, lm_head), so attention's collectives are the only cross-shard
exchange in the whole forward.

Mechanics:

- the WHOLE loss runs inside one ``shard_map`` over ``(data, sequence)``;
  parameters enter replicated (in_spec ``P()``) and shard_map's
  transpose psums their cotangents automatically, so ``jax.grad``
  through the shard_map yields exact global gradients with no manual
  collectives;
- RoPE positions are global: each shard offsets by
  ``axis_index(sequence) * S_local``;
- next-token targets are built OUTSIDE the shard_map by shifting the
  full sequence (last global position gets ``ignore_id``), so the
  shard-boundary token never needs a neighbor exchange;
- the loss is a masked-CE ratio of two ``psum``s (token sums over both
  mesh axes), replicated on every device;
- MoE composes: each ``MoEMlp`` sows its token-mean routing/gate
  fractions (``moe_stats``); the step pmeans them over the mesh axes and
  re-forms the load-balance loss ``E * sum(rf * gf)`` from the GLOBAL
  fractions — exactly serial ``lm_step``'s aux, since the fractions are
  token means over equal-size shards (a mean of per-shard aux products
  would NOT match).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax

from unionml_tpu.models.llama import Llama, LlamaConfig
from unionml_tpu.models.train import TrainState


def sequence_parallel_config(
    cfg: LlamaConfig, *, attn: str = "ring", seq_axis: str = "sequence"
) -> LlamaConfig:
    """The same model with sequence-sharded attention bound to the axis.

    ``attn``: ``"ring"`` / ``"ring_flash"`` (ppermute KV rotation) or
    ``"ulysses"`` (all-to-all head<->sequence reshuffle; requires q AND
    kv head counts divisible by the axis size).
    """
    if attn not in ("ring", "ring_flash", "ulysses"):
        raise ValueError(
            f"sequence-parallel attention must be ring/ring_flash/ulysses, got {attn!r}"
        )
    return LlamaConfig(
        **{**cfg.__dict__, "attn_impl": attn, "sequence_axis": seq_axis}
    )


def sequence_parallel_lm_step(
    cfg: LlamaConfig,
    *,
    mesh,
    attn: str = "ring",
    data_axis: Optional[str] = "data",
    seq_axis: str = "sequence",
    ignore_id: int = -100,
    aux_loss_weight: float = 0.01,
) -> Callable:
    """``step(state, tokens[B, S]) -> (state, metrics)`` with the sequence
    dimension sharded over ``mesh[seq_axis]``.

    ``S`` must divide by the sequence axis size; ``B`` by the data axis.
    jit the returned step (e.g. via ``compile_step`` with a
    ``ShardingConfig(data=m, sequence=n)`` — parameters replicate, the
    batch spec shards [B, S] over (data, sequence)).
    """
    from unionml_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    sp_cfg = sequence_parallel_config(cfg, attn=attn, seq_axis=seq_axis)
    n_seq = mesh.shape[seq_axis]
    kv_heads = cfg.num_kv_heads or cfg.num_heads
    if attn == "ulysses" and (cfg.num_heads % n_seq or kv_heads % n_seq):
        # fail at config time, not deep inside jit tracing
        raise ValueError(
            f"ulysses needs q heads ({cfg.num_heads}) and kv heads "
            f"({kv_heads}) divisible by the sequence axis size "
            f"({n_seq}); use ring/ring_flash instead"
        )
    module = Llama(sp_cfg)
    axes = (data_axis, seq_axis) if data_axis else (seq_axis,)

    def local_loss_sums(params, tok_shard, tgt_shard):
        """-> (ce_sum, token_count, moe fraction leaves) for this shard."""
        s_loc = tok_shard.shape[1]
        positions = lax.axis_index(seq_axis) * s_loc + jnp.arange(s_loc)[None, :]
        logits, mods = module.apply(
            {"params": params}, tok_shard, positions=positions,
            mutable=["moe_stats"],
        )
        logits = logits.astype(jnp.float32)
        mask = (tgt_shard != ignore_id).astype(jnp.float32)
        safe = jnp.where(tgt_shard == ignore_id, 0, tgt_shard)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, safe)
        fracs = jax.tree_util.tree_leaves(mods.get("moe_stats", {}))
        return (ce * mask).sum(), mask.sum(), fracs

    def sharded_loss(params, tokens, targets):
        ce_sum, count, fracs = local_loss_sums(params, tokens, targets)
        for ax in axes:
            ce_sum = lax.psum(ce_sum, ax)
            count = lax.psum(count, ax)
            # token-MEAN fractions: shards hold equal token counts, so the
            # pmean over shards is exactly the global token mean
            fracs = [lax.pmean(f, ax) for f in fracs]
        ce = ce_sum / jnp.maximum(count, 1.0)
        if fracs:
            # re-form the load-balance loss from GLOBAL fractions (same
            # formula as ops/moe.py top_k_routing) — exactly the serial
            # lm_step aux, unlike a mean of per-shard products
            per_layer = [
                cfg.num_experts * jnp.sum(f[0] * f[1]) for f in fracs
            ]
            aux = sum(per_layer) / len(per_layer)
        else:
            aux = jnp.float32(0.0)
        return ce + aux_loss_weight * aux, (ce, aux)

    batch_spec = P(data_axis, seq_axis) if data_axis else P(None, seq_axis)
    loss_sm = shard_map(
        sharded_loss,
        mesh=mesh,
        in_specs=(P(), batch_spec, batch_spec),
        out_specs=(P(), (P(), P())),
        check_vma=False,
    )

    def step(state: TrainState, tokens: jnp.ndarray):
        # global shift: target of the last position is ignore_id, so shard
        # boundaries never need the neighbor's first token
        targets = jnp.concatenate(
            [tokens[:, 1:], jnp.full((tokens.shape[0], 1), ignore_id, tokens.dtype)],
            axis=1,
        )

        (_, (loss, aux)), grads = jax.value_and_grad(
            lambda p: loss_sm(p, tokens, targets), has_aux=True
        )(state.params)
        state = state.apply_gradients(grads=grads)
        return state, {
            "loss": loss,
            "perplexity": jnp.exp(loss),
            "aux_loss": aux,
        }

    return step
