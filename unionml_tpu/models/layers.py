"""Shared TPU-first building blocks for the model zoo.

No reference counterpart — the reference delegates modeling to
sklearn/torch/keras user code (reference: unionml/model.py:931-988 only
touches models to serialize them). Here the framework ships its own
flax.linen model family (BASELINE.json configs: MNIST-MLP, ViT-B/16,
BERT-base, Llama-3-8B) so trainer/predictor bodies are jit/pjit-native.

Design notes (TPU):
- All matmul-bearing layers keep a ``dtype`` (compute, default bfloat16)
  separate from ``param_dtype`` (float32 master weights) so the MXU runs
  bf16 while optimizer state stays fp32.
- Attention dispatches to the op family in :mod:`unionml_tpu.ops` —
  ``xla`` (fused reference), ``blockwise`` (online-softmax memory saver),
  ``flash`` (Pallas kernel), ``ring``/``ulysses`` (sequence-parallel,
  require a mesh axis).
- Kernel axes are named via ``nn.with_logical_partitioning``-free plain
  params; tensor-parallel layouts come from path-regex
  :class:`~unionml_tpu.parallel.sharding.PartitionRule`s instead, keeping
  modules decoupled from the mesh.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from unionml_tpu.ops.attention import attention as xla_attention
from unionml_tpu.ops.attention import blockwise_attention

Dtype = Any


def make_dense(
    *,
    quantized: bool,
    features,
    name: str,
    dtype: Dtype,
    axis=-1,
    param_dtype: Dtype = jnp.float32,
    use_bias: bool = False,
    lora_rank: int = 0,
    lora_alpha: float = 16.0,
    weight_bits: int = 8,
    int4_group: int = 0,
    int4_shards: int = 1,
):
    """Dense-projection factory shared by every matmul site that supports
    the int8 weight-only serving path (Attention qkv/o, gated MLP,
    lm_head): one place to extend quantized-layer construction.

    ``lora_rank > 0`` swaps in :class:`~unionml_tpu.models.lora.
    LoRADenseGeneral` — same base parameter paths (fp ``kernel`` or int8
    ``kernel_q``+``scale``) plus trainable ``lora_a``/``lora_b`` adapters
    (QLoRA when combined with ``quantized=True``)."""
    if lora_rank > 0:
        # adapters compose with the fp or INT8 base only — silently
        # dropping an int4 request would train against the wrong base
        assert weight_bits == 8, "LoRA/QLoRA requires weight_bits=8"
        from unionml_tpu.models.lora import LoRADenseGeneral

        return LoRADenseGeneral(
            features=features, axis=axis, lora_rank=lora_rank,
            lora_alpha=lora_alpha, quantized=quantized, use_bias=use_bias,
            dtype=dtype, param_dtype=param_dtype, name=name,
        )
    if quantized:
        assert not use_bias, "quantized dense layers are bias-free"
        if weight_bits == 4:
            from unionml_tpu.models.quantization import Int4DenseGeneral

            return Int4DenseGeneral(
                features=features, axis=axis, dtype=dtype, name=name,
                group_size=int4_group, shards=int4_shards,
            )
        from unionml_tpu.models.quantization import QuantizedDenseGeneral

        return QuantizedDenseGeneral(features=features, axis=axis, dtype=dtype, name=name)
    return nn.DenseGeneral(
        features=features, axis=axis, use_bias=use_bias, dtype=dtype,
        param_dtype=param_dtype, name=name,
    )


class RMSNorm(nn.Module):
    """Root-mean-square norm (Llama-style, no mean subtraction).

    ``impl="fused"`` routes through the Pallas kernel pair
    (:mod:`unionml_tpu.ops.fused_norm`) — same math (fp32 statistics,
    cast once), one fused pass per direction.
    """

    eps: float = 1e-5
    dtype: Dtype = jnp.bfloat16
    impl: str = "xla"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        if self.impl == "fused":
            from unionml_tpu.ops.fused_norm import fused_rms_norm

            return fused_rms_norm(x, scale, eps=self.eps).astype(self.dtype)
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (normed * scale).astype(self.dtype)


class LayerNorm(nn.Module):
    """LayerNorm through the fused Pallas kernel pair
    (:mod:`unionml_tpu.ops.fused_norm`), parameter-path compatible with
    ``nn.LayerNorm`` (``scale``/``bias`` at this module's level —
    checkpoints interchange freely).

    Model configs select the implementation at the CALL SITE: the
    default "xla" norm_impl uses plain ``nn.LayerNorm`` (identical graph
    and numerics for existing users — a wrapper here would either nest
    the param path or re-implement flax's statistics), and this module
    is instantiated only on the fused path.
    """

    eps: float = 1e-6
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        from unionml_tpu.ops.fused_norm import fused_layer_norm

        d = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (d,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (d,), jnp.float32)
        return fused_layer_norm(x, scale, bias, self.eps).astype(self.dtype)


def llama3_rope_frequencies(
    freqs: jnp.ndarray,
    *,
    factor: float,
    low_freq_factor: float,
    high_freq_factor: float,
    original_max_len: int,
) -> jnp.ndarray:
    """Llama-3.1/3.2 long-context RoPE frequency rescaling.

    Wavelengths shorter than ``original_max_len / high_freq_factor`` keep
    their frequency, longer than ``original_max_len / low_freq_factor``
    divide by ``factor``, and the band between interpolates smoothly —
    the "llama3" ``rope_scaling`` scheme HF checkpoints carry in
    config.json. Verified against transformers' torch implementation in
    ``tests/unit/test_convert_hf_parity.py``.
    """
    wavelen = 2.0 * np.pi / freqs
    ratio = original_max_len / wavelen
    smooth = (ratio - low_freq_factor) / (high_freq_factor - low_freq_factor)
    smooth = jnp.clip(smooth, 0.0, 1.0)
    return ((1.0 - smooth) / factor + smooth) * freqs


def rotary_embedding(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    *,
    theta: float = 10_000.0,
    scaling: Optional[Tuple[float, float, float, int]] = None,
) -> jnp.ndarray:
    """Apply rotary position embedding to ``x`` of shape (..., seq, heads, head_dim).

    ``positions``: integer array broadcastable to (..., seq). Llama-3 uses
    ``theta=500_000`` for long-context; classic RoPE uses 10_000.
    ``scaling``: optional llama3-type frequency rescale as a
    ``(factor, low_freq_factor, high_freq_factor, original_max_len)``
    tuple (hashable — it rides inside frozen model configs).
    """
    head_dim = x.shape[-1]
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    if scaling is not None:
        factor, low, high, orig = scaling
        freqs = llama3_rope_frequencies(
            freqs, factor=factor, low_freq_factor=low,
            high_freq_factor=high, original_max_len=orig,
        )
    angles = positions[..., None].astype(jnp.float32) * freqs  # (..., seq, half)
    cos = jnp.cos(angles)[..., None, :]  # (..., seq, 1, half)
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


# the dispatcher's accepted impl names — validate against this instead of
# maintaining per-model copies
ATTN_IMPLS = (
    "auto", "xla", "blockwise", "flash", "fused", "ring", "ring_flash", "ulysses"
)


def _run_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    impl: str,
    causal: bool,
    sequence_axis: Optional[str],
) -> jnp.ndarray:
    """Dispatch (batch, seq, heads, head_dim) tensors to an attention op.

    ``"auto"`` picks fused below the measured short-seq crossover (equal
    q/kv lengths only), flash above it.
    """
    if impl == "auto":
        from unionml_tpu.ops.fused_attention import MAX_FUSED_SEQ

        impl = (
            "fused"
            if q.shape[1] <= MAX_FUSED_SEQ and k.shape[1] == q.shape[1]
            else "flash"
        )
    if impl == "xla":
        return xla_attention(q, k, v, causal=causal)
    if impl == "blockwise":
        return blockwise_attention(q, k, v, causal=causal)
    if impl == "flash":
        from unionml_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)
    if impl == "fused":
        from unionml_tpu.ops.fused_attention import fused_attention

        return fused_attention(q, k, v, causal=causal)
    if impl == "ring":
        from unionml_tpu.ops.ring_attention import ring_attention_sharded

        assert sequence_axis, "ring attention needs a sequence mesh axis"
        return ring_attention_sharded(q, k, v, axis=sequence_axis, causal=causal)
    if impl == "ring_flash":
        from unionml_tpu.ops.ring_attention import ring_flash_attention_sharded

        assert sequence_axis, "ring attention needs a sequence mesh axis"
        return ring_flash_attention_sharded(
            q, k, v, axis=sequence_axis, causal=causal
        )
    if impl == "ulysses":
        from unionml_tpu.ops.ulysses import ulysses_attention_sharded

        assert sequence_axis, "ulysses attention needs a sequence mesh axis"
        # the inner attention sees the FULL gathered sequence: "auto"
        # (fused short / flash long) keeps it memory-efficient instead of
        # materializing O(S^2) scores at the lengths SP targets
        return ulysses_attention_sharded(
            q, k, v, axis=sequence_axis, causal=causal, impl="auto"
        )
    raise ValueError(f"unknown attention impl {impl!r}")


class Attention(nn.Module):
    """Multi-head attention with grouped-query support and optional KV cache.

    Param layout: q/k/v/o projections as single dense kernels whose head
    axis is foldable for tensor parallelism (rules match ``attn/(q|k|v)``
    paths and shard the output features over the ``tensor`` axis; ``attn/o``
    shards input features, so TP needs exactly one psum per block — the
    Megatron layout realized by GSPMD instead of hand-written collectives).
    """

    num_heads: int
    num_kv_heads: Optional[int] = None  # GQA; None → MHA
    head_dim: Optional[int] = None
    rope: bool = False
    rope_theta: float = 10_000.0
    # llama3-type long-context frequency rescale:
    # (factor, low_freq_factor, high_freq_factor, original_max_len)
    rope_scaling: Optional[Tuple[float, float, float, int]] = None
    causal: bool = False
    attn_impl: str = "xla"
    # attention impl for FULL prefills (multi-token call on an empty
    # cache): "cached" = the masked cached_attention path (materializes
    # [B, H, S, max_len] fp32 scores — ~8 GB at 8B x 8k); "flash" = the
    # Pallas flash kernel over the FRESH post-RoPE k/v with a per-row
    # left-pad mask (no score buffer, the long-prefill memory/speed
    # lever). Only consulted when the caller passes full_prefill=True.
    prefill_impl: str = "cached"
    # decode attention impl for block-paged KV pools (the engine's
    # paged mode; only consulted when the caller passes block_table=):
    # "reference" = jnp.take gather, bit-identical to the contiguous
    # cache path; "pallas" = the scalar-prefetch gather kernel; "auto"
    # = pallas on TPU, reference elsewhere (ops/paged_attention.py).
    paged_impl: str = "auto"
    sequence_axis: Optional[str] = None
    quantized: bool = False  # weight-only quantized projections (serving)
    weight_bits: int = 8     # 8 = int8; 4 = packed-int4 (decode bandwidth)
    int4_group: int = 0      # >0: group-wise int4 scales (scale_g [K/g, N])
    int4_tp: int = 1         # TP degree the int4 packing must survive
    lora_rank: int = 0  # >0: trainable low-rank adapters on q/k/v/o
    lora_alpha: float = 16.0
    # biases on q/k/v/o (HF ViT/BERT-style checkpoints carry them; the
    # zoo's trained-from-scratch defaults stay bias-free)
    use_bias: bool = False
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        *,
        kv: Optional[jnp.ndarray] = None,
        positions: Optional[jnp.ndarray] = None,
        cache: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
        cache_index: Optional[jnp.ndarray] = None,
        kv_mask: Optional[jnp.ndarray] = None,
        block_table: Optional[jnp.ndarray] = None,
        full_prefill: bool = False,
    ):
        """Returns ``out`` or ``(out, new_cache)`` when a cache is given.

        ``block_table``: int32 [batch, table_width] — marks ``cache`` as
        a BLOCK-PAGED pool (per buffer [num_blocks, block, kv_heads,
        head_dim]; int8 pools carry [num_blocks, block, kv_heads] scale
        planes) addressed through the table (entries past a row's
        coverage point at the trash block). Decode-step only: requires
        ``seq == 1`` and a vector ``cache_index`` (per-row fills); the
        step's k/v row scatters into pool block ``table[b, fill //
        block]`` at offset ``fill % block``, and attention reads
        through :func:`~unionml_tpu.ops.paged_attention.paged_attention`
        (``paged_impl`` picks the kernel) with ``lengths = fill + 1``
        (the just-written row sees itself). ``kv_mask`` must be None —
        visibility is derived from the fills.

        ``full_prefill``: STATIC caller promise that this multi-token
        cached call covers the entire visible history — the cache is
        empty, ``cache_index == 0``, and there is no shared prefix — so
        attention may run over the fresh k/v alone (``prefill_impl``
        decides how). The promise cannot be checked here (cache_index is
        traced); passing it on a chunked or prefix prefill silently drops
        the earlier context.

        ``kv``: optional (batch, kv_seq, features) source for CROSS
        attention — k/v project from it instead of ``x`` (q still from
        ``x``). Requires ``causal=False``, ``rope=False`` and no cache:
        the whole source is always visible, and inside a decode
        ``lax.scan`` the loop-invariant k/v projections are hoisted by
        XLA, so no cross-KV cache plumbing is needed. ``kv_mask`` then
        masks padded SOURCE positions ((batch, kv_seq), False = hidden).
        ``cache``: (k, v) of shape (batch, max_len, kv_heads, head_dim);
        ``cache_index``: current fill position (decode step) — a scalar
        int shared by every row, or an int vector ``[batch]`` of per-row
        fill positions (continuous-batching decode, where in-flight
        sequences sit at different depths);
        ``kv_mask``: optional bool (batch, max_len) — False slots are
        never attended to (left-padded prompts in generation).
        """
        batch, seq, features = x.shape
        kv_heads = self.num_kv_heads or self.num_heads
        head_dim = self.head_dim or features // self.num_heads
        dense = lambda feats, name, shards=1: make_dense(  # noqa: E731
            quantized=self.quantized, features=feats, axis=-1,
            dtype=self.dtype, param_dtype=self.param_dtype, name=name,
            lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
            use_bias=self.use_bias, weight_bits=self.weight_bits,
            int4_group=self.int4_group, int4_shards=shards,
        )
        # q/k/v are COLUMN-parallel under TP (N sharded): their int4
        # packing tile must divide the per-device channel count. o is
        # row-parallel (K sharded, N whole) — shards stays 1.
        q = dense((self.num_heads, head_dim), "q", self.int4_tp)(x)
        if kv is not None:
            if self.causal or self.rope or cache is not None:
                raise ValueError(
                    "cross attention (kv=...) is incompatible with causal "
                    "masking, RoPE, and KV caches — the source is fully "
                    "visible and position-free"
                )
            k = dense((kv_heads, head_dim), "k")(kv)
            v = dense((kv_heads, head_dim), "v")(kv)
            # always the XLA op: q_len != kv_len in general (the Pallas
            # short-seq kernel assumes square score tiles), and XLA fuses
            # the modest [S_dec, S_enc] score chain well
            bias = (
                jnp.where(kv_mask[:, None, None, :], 0.0, -1e30)
                if kv_mask is not None
                else None
            )
            out = xla_attention(q, k, v, bias=bias)
            return make_dense(
                quantized=self.quantized, features=features, axis=(-2, -1),
                dtype=self.dtype, param_dtype=self.param_dtype, name="o",
                lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
                use_bias=self.use_bias, weight_bits=self.weight_bits,
                int4_group=self.int4_group,
            )(out)
        k = dense((kv_heads, head_dim), "k", self.int4_tp)(x)
        v = dense((kv_heads, head_dim), "v", self.int4_tp)(x)

        if positions is None:
            base = jnp.asarray(cache_index if cache_index is not None else 0)
            if base.ndim == 1:
                base = base[:, None]  # per-row fill positions (slot decode)
            positions = base + jnp.arange(seq)[None, :]
        if self.rope:
            q = rotary_embedding(
                q, positions, theta=self.rope_theta, scaling=self.rope_scaling
            )
            k = rotary_embedding(
                k, positions, theta=self.rope_theta, scaling=self.rope_scaling
            )

        new_cache = None
        if cache is not None:
            index = jnp.asarray(cache_index)
            if block_table is not None:
                # block-paged pool: decode-step writes scatter into the
                # table-addressed block row. The engine masks retired
                # slots' table rows to the trash block per step, so a
                # dead slot's write can never corrupt a recycled block.
                if seq != 1 or index.ndim != 1:
                    raise ValueError(
                        "block-paged caches support vector-index decode "
                        f"steps only (seq == 1), got seq={seq}, "
                        f"cache_index ndim {index.ndim}"
                    )
                if kv_mask is not None:
                    raise ValueError(
                        "kv_mask is incompatible with block_table — "
                        "paged visibility derives from the fills"
                    )
                blk = cache[0].shape[1]
                pid = jnp.take_along_axis(
                    block_table, (index // blk)[:, None], axis=1
                )[:, 0]
                off = index % blk

            def upd(buf, new, idx=index):
                # paged: one advanced-index scatter at (block, offset);
                # scalar index: one dynamic_update_slice at [_, idx, ...];
                # vector [batch] index: a vmapped slice-update (one scatter)
                # — the continuous-batching decode step where each slot
                # writes at its own depth
                new = new.astype(buf.dtype)
                if block_table is not None:
                    return buf.at[pid, off].set(new[:, 0])
                if idx.ndim == 1:
                    one = lambda c, n, i: jax.lax.dynamic_update_slice(  # noqa: E731
                        c, n, (i,) + (0,) * (c.ndim - 1)
                    )
                    return jax.vmap(one)(buf, new, idx)
                return jax.lax.dynamic_update_slice(
                    buf, new, (0, idx) + (0,) * (buf.ndim - 2)
                )

            if len(cache) == 4:
                # int8-quantized KV cache: (k_q, v_q, k_scale, v_scale),
                # scales per (batch, position, kv_head). Halves cache HBM
                # (the long-context serving bound) at the cost of one
                # int8 grid rounding per written position; the dequant
                # multiply fuses into the attention matmul reads.
                ck, cv, ks, vs = cache

                def quantize(x):
                    x32 = x.astype(jnp.float32)
                    s = jnp.max(jnp.abs(x32), axis=-1) / 127.0  # [B,S,H]
                    s = jnp.maximum(s, 1e-8)
                    q = jnp.clip(
                        jnp.round(x32 / s[..., None]), -127, 127
                    ).astype(jnp.int8)
                    return q, s

                k_q, k_s = quantize(k)
                v_q, v_s = quantize(v)
                ck, cv = upd(ck, k_q), upd(cv, v_q)
                ks, vs = upd(ks, k_s), upd(vs, v_s)
                new_cache = (ck, cv, ks, vs)
            else:
                ck, cv = cache
                ck, cv = upd(ck, k), upd(cv, v)
                new_cache = (ck, cv)
            out = None
            if block_table is not None:
                # paged decode read: gather-attend through the block
                # table (no contiguous cache view is ever materialized
                # on the kernel path); lengths = fill + 1 exposes the
                # row this step just wrote, matching the contiguous
                # path's self-visible kv_mask row
                from unionml_tpu.ops.paged_attention import paged_attention

                if len(cache) == 4:
                    out = paged_attention(
                        q[:, 0], ck, cv, block_table, index + 1,
                        k_scale=ks, v_scale=vs, impl=self.paged_impl,
                    )[:, None]
                else:
                    out = paged_attention(
                        q[:, 0], ck, cv, block_table, index + 1,
                        impl=self.paged_impl,
                    )[:, None]
            if full_prefill and seq > 1 and self.prefill_impl == "flash":
                # full-history prefill: attention over the FRESH post-RoPE
                # k/v through the Pallas flash kernel — no [B,H,S,max_len]
                # score buffer (the 8k x 8B OOM), better MXU tiling than
                # max_len-wide masked chunks. Left padding masks via the
                # kernel's per-row kv_valid_start (contiguous by the
                # generator's construction). With an int8 KV cache the
                # decode path reads quantized k/v while this reads exact —
                # slightly MORE accurate than the cached prefill.
                from unionml_tpu.ops.flash_attention import flash_attention

                # per-row LEADING-invalid count (argmax finds the first
                # True). Left-padded prompts (generate) get their pad
                # count; right-padded buckets (the engine's admissions)
                # get 0 — causal masking alone already hides trailing
                # garbage from every real query, and the garbage rows'
                # outputs/cache slots are discarded/masked downstream.
                pads = (
                    jnp.zeros((batch,), jnp.int32)
                    if kv_mask is None
                    else jnp.argmax(
                        kv_mask[:, :seq].astype(jnp.int32), axis=-1
                    ).astype(jnp.int32)
                )
                out = flash_attention(q, k, v, causal=True, kv_valid_start=pads)
            if out is None:
                # attend over the filled prefix only: kv slot j is visible
                # to query i iff j <= cache_index + i (covers decode seq=1
                # and cached prefill seq>1; unwritten slots are masked out)
                kv_pos = jnp.arange(ck.shape[1])[None, :]
                if index.ndim == 1:
                    q_pos = index[:, None, None] + jnp.arange(seq)[None, :, None]
                    visible = kv_pos[None] <= q_pos         # (batch, seq, max_len)
                    if kv_mask is not None:
                        visible = visible & kv_mask[:, None, :]
                    bias = jnp.where(visible, 0.0, -1e30)[:, None]
                else:
                    q_pos = index + jnp.arange(seq)[:, None]
                    visible = kv_pos <= q_pos               # (seq, max_len)
                    if kv_mask is not None:
                        # (batch, 1, seq, max_len): padded slots stay invisible
                        visible = visible[None] & kv_mask[:, None, :]
                        bias = jnp.where(visible, 0.0, -1e30)[:, None]
                    else:
                        bias = jnp.where(visible, 0.0, -1e30)[None, None]
                if len(cache) == 4:
                    from unionml_tpu.ops.attention import quantized_cache_attention

                    out = quantized_cache_attention(q, ck, cv, ks, vs, bias=bias)
                else:
                    # grouped GQA path: reads the cache at kv-head width (no
                    # repeat — measured 2x decode at 1.5B) and block-scans
                    # past the VMEM limit at long context
                    from unionml_tpu.ops.attention import cached_attention

                    out = cached_attention(
                        q, ck.astype(self.dtype), cv.astype(self.dtype), bias=bias
                    )
        else:
            out = _run_attention(
                q, k, v,
                impl=self.attn_impl,
                causal=self.causal,
                sequence_axis=self.sequence_axis,
            )
        out = make_dense(
            quantized=self.quantized, features=features, axis=(-2, -1),
            dtype=self.dtype, param_dtype=self.param_dtype, name="o",
            lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
            use_bias=self.use_bias, weight_bits=self.weight_bits,
            int4_group=self.int4_group,
        )(out)
        if cache is not None:
            return out, new_cache
        return out


class MlpBlock(nn.Module):
    """Transformer MLP: GELU (ViT/BERT) or SwiGLU (Llama)."""

    hidden_dim: int
    gated: bool = False  # True → SwiGLU
    quantized: bool = False  # weight-only quantized (bias-free gated form only)
    weight_bits: int = 8
    int4_group: int = 0      # >0: group-wise int4 scales (scale_g [K/g, N])
    int4_tp: int = 1         # TP degree the int4 packing must survive
    lora_rank: int = 0  # >0: trainable low-rank adapters on gate/up/down
    lora_alpha: float = 16.0
    # tanh-approximate GELU by default (one transcendental cheaper on the
    # VPU); HF BERT checkpoints were trained with erf GELU — loaders set
    # False for checkpoint-faithful inference (models/convert.py)
    gelu_approximate: bool = True
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        features = x.shape[-1]
        if self.quantized:
            assert self.gated, "quantized MlpBlock supports the bias-free gated form"
        dense = lambda feats, name, shards=1: make_dense(  # noqa: E731
            quantized=self.quantized, features=feats, dtype=self.dtype,
            param_dtype=self.param_dtype, use_bias=not self.gated, name=name,
            lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
            weight_bits=self.weight_bits,
            int4_group=self.int4_group, int4_shards=shards,
        )
        if self.gated:
            # gate/up are column-parallel under TP (N sharded): their
            # int4 tile must divide the per-device width; down is
            # row-parallel and keeps shards=1
            gate = nn.silu(dense(self.hidden_dim, "gate", self.int4_tp)(x))
            up = dense(self.hidden_dim, "up", self.int4_tp)(x)
            return dense(features, "down")(gate * up)
        h = nn.gelu(
            dense(self.hidden_dim, "up")(x), approximate=self.gelu_approximate
        )
        return dense(features, "down")(h)
