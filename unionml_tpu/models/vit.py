"""ViT — the data-parallel training flagship (BASELINE.json config #3,
"ViT-B/16 image classifier (pjit data-parallel over v5e-8 mesh)").

TPU-first choices: patchify is one strided conv (a big MXU matmul after
im2col — XLA lowers it directly), the encoder body is a `lax.scan`-free
stack of identical blocks (XLA caches the compiled block), compute in
bf16 with fp32 LayerNorm statistics, and the TP partition rules below give
the Megatron 2-collectives-per-block layout via GSPMD.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp
from flax import linen as nn

from unionml_tpu.models.layers import Attention, LayerNorm, MlpBlock
from unionml_tpu.parallel.sharding import PartitionRule


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    attn_impl: str = "xla"
    # "fused" = Pallas LayerNorm kernel pair incl. residual-add fusion
    # (ops/fused_norm.py); "xla" = plain fp32-stats LayerNorm
    norm_impl: str = "xla"
    # HF ViT checkpoints carry q/k/v/o biases and use erf GELU; the
    # trained-from-scratch defaults stay bias-free/tanh. Checkpoint
    # loaders (models/convert.py) set both for faithful inference.
    qkv_bias: bool = False
    gelu_exact: bool = False
    dtype: str = "bfloat16"

    @staticmethod
    def base16(num_classes: int = 1000, attn_impl: str = "fused") -> "ViTConfig":
        # "fused" = Pallas one-program-per-batch attention: at S=197 it
        # beats XLA attention ~1.6x fwd+bwd on v5e (see ops/fused_attention)
        return ViTConfig(num_classes=num_classes, attn_impl=attn_impl)

    @staticmethod
    def tiny(image_size: int = 32, num_classes: int = 10) -> "ViTConfig":
        return ViTConfig(
            image_size=image_size, patch_size=8, num_classes=num_classes,
            hidden_dim=64, num_layers=2, num_heads=4, mlp_dim=128,
        )


class ViTBlock(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        # default path stays plain nn.LayerNorm (identical graph/numerics
        # to pre-norm_impl builds); the fused module shares its param
        # names so either impl loads the other's checkpoints
        ln = lambda name: (  # noqa: E731
            LayerNorm(dtype=dtype, name=name)
            if cfg.norm_impl == "fused"
            else nn.LayerNorm(dtype=dtype, name=name)
        )
        attn = Attention(
            num_heads=cfg.num_heads, attn_impl=cfg.attn_impl,
            use_bias=cfg.qkv_bias, dtype=dtype, name="attn",
        )
        mlp = MlpBlock(
            hidden_dim=cfg.mlp_dim, gelu_approximate=not cfg.gelu_exact,
            dtype=dtype, name="mlp",
        )
        if cfg.norm_impl == "fused":
            # fuse the mid-block residual add into ln2's pass (one fewer
            # [B*S, D] HBM round trip each way); param tree unchanged
            h1 = ln("ln1")(x)
            s, h2 = _AddLayerNorm(dtype=cfg.dtype, name="ln2")(x, attn(h1))
            return s + mlp(h2)
        x = x + attn(ln("ln1")(x))
        x = x + mlp(ln("ln2")(x))
        return x


class _AddLayerNorm(nn.Module):
    """``s = x + branch; y = LayerNorm(s)`` through the fused kernel,
    parameter-compatible with :class:`LayerNorm` (``scale``/``bias``)."""

    eps: float = 1e-6
    dtype: str = "bfloat16"

    @nn.compact
    def __call__(self, x: jnp.ndarray, branch: jnp.ndarray):
        from unionml_tpu.ops.fused_norm import fused_add_layer_norm

        d = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (d,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (d,), jnp.float32)
        s, y = fused_add_layer_norm(x, branch, scale, bias, self.eps)
        return s, y.astype(jnp.dtype(self.dtype))


class ViT(nn.Module):
    config: ViTConfig = field(default_factory=ViTConfig)

    @nn.compact
    def __call__(self, images: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        p = cfg.patch_size
        # patchify: one conv == one big MXU matmul
        x = nn.Conv(
            cfg.hidden_dim, kernel_size=(p, p), strides=(p, p),
            padding="VALID", dtype=dtype, name="patch_embed",
        )(images.astype(dtype))
        batch = x.shape[0]
        x = x.reshape((batch, -1, cfg.hidden_dim))
        cls = self.param(
            "cls", nn.initializers.zeros, (1, 1, cfg.hidden_dim), jnp.float32
        ).astype(dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls, (batch, 1, cfg.hidden_dim)), x], axis=1)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, x.shape[1], cfg.hidden_dim),
            jnp.float32,
        )
        x = x + pos.astype(dtype)
        for i in range(cfg.num_layers):
            x = ViTBlock(cfg, name=f"block_{i}")(x)
        if cfg.norm_impl == "fused":
            x = LayerNorm(dtype=dtype, name="ln_final")(x)
        else:
            x = nn.LayerNorm(dtype=dtype, name="ln_final")(x)
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(x[:, 0])


# Megatron-style TP: qkv/up split output features over `tensor`,
# o/down split input features → one psum after attn, one after mlp.
VIT_PARTITION_RULES = (
    PartitionRule(r"attn/(q|k|v)/kernel$", (None, "tensor", None)),
    PartitionRule(r"attn/o/kernel$", ("tensor", None, None)),
    PartitionRule(r"mlp/up/kernel$", (None, "tensor")),
    PartitionRule(r"mlp/down/kernel$", ("tensor", None)),
    PartitionRule(r"patch_embed/kernel$", (None, None, None, "tensor")),
)
