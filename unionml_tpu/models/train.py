"""Train-state and step-function factories for the model zoo.

The reference leaves training loops to user code (SURVEY.md §3.1: "the hot
loop lives entirely in the user trainer body"). Here the framework supplies
jit-ready ``step(state, batch) -> (state, metrics)`` functions matching the
:meth:`unionml_tpu.model.Model.train_step` contract, so a zoo model trains
with three lines of app code. Loss math runs in fp32 (bf16 params upcast at
the loss) and gradients are computed by a single ``jax.value_and_grad``
program — XLA fuses the whole step into one executable per shape.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax.training import train_state


class TrainState(train_state.TrainState):
    """flax TrainState (params + optax state + apply_fn + step counter)."""


def adamw(learning_rate: float, *, weight_decay: float = 0.0,
          b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8):
    """AdamW as an explicit optax chain.

    Mathematically identical to ``optax.adamw``, but ``optax.adamw``
    triggers a ~4x whole-step slowdown under buffer donation on TPU
    (measured on v5e, BERT-base 110M params: 83.5 ms/step vs 20.3 ms for
    this chain — see BASELINE.md); the explicit composition compiles
    clean under donated state.
    """
    steps = [optax.scale_by_adam(b1=b1, b2=b2, eps=eps)]
    if weight_decay:
        steps.append(optax.add_decayed_weights(weight_decay))
    # scale_by_learning_rate accepts floats AND schedules, like optax.adamw
    steps.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*steps)


def create_train_state(
    module: nn.Module,
    example_input: Any,
    *,
    optimizer: Optional[optax.GradientTransformation] = None,
    learning_rate: float = 1e-3,
    weight_decay: float = 0.0,
    seed: int = 0,
    init_kwargs: Optional[dict] = None,
) -> TrainState:
    """Initialize parameters from an example batch and wrap with optax.

    Default optimizer is :func:`adamw` (the donation-safe chain) — the
    optimizer state duplicates the param pytree twice, so under FSDP the
    same partition rules shard it too (ShardingConfig.state_shardings
    walks the whole TrainState).
    """
    params = module.init(
        jax.random.PRNGKey(seed), example_input, **(init_kwargs or {})
    )["params"]
    tx = optimizer or adamw(learning_rate, weight_decay=weight_decay)
    return TrainState.create(apply_fn=module.apply, params=params, tx=tx)


def _accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))


def masked_cross_entropy(
    logits: jnp.ndarray, targets: jnp.ndarray, *, ignore_id: int = -100
) -> jnp.ndarray:
    """Mean CE over positions where ``targets != ignore_id`` (fp32 math).

    Shared by the serial :func:`lm_step` and the pipelined trainer
    (models/pipeline_lm.py) so their losses cannot drift apart.
    """
    logits = logits.astype(jnp.float32)
    mask = (targets != ignore_id).astype(jnp.float32)
    safe = jnp.where(targets == ignore_id, 0, targets)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, safe)
    return (ce * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def classification_step(module: nn.Module) -> Callable:
    """softmax-CE step for (features, int_labels) batches (MLP/ViT/BERT-cls)."""

    def step(state: TrainState, batch: Tuple[Any, Any]):
        features, labels = batch

        def loss_fn(params):
            logits = state.apply_fn({"params": params}, features)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels
            ).mean()
            return loss, logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        state = state.apply_gradients(grads=grads)
        return state, {"loss": loss, "accuracy": _accuracy(logits, labels)}

    return step


def lm_step(
    module: nn.Module, *, ignore_id: int = -100, aux_loss_weight: float = 0.01
) -> Callable:
    """Next-token LM step: batch is token ids [B, S]; loss over shifted pairs.

    Also accepts ``(tokens, labels)`` for masked-LM/fine-tune batches where
    labels carry ``ignore_id`` at unsupervised positions.

    MoE modules sow per-layer load-balancing losses into the
    ``aux_losses`` collection (ops/moe.py); their layer-mean is added to
    the CE loss scaled by ``aux_loss_weight`` and reported as the
    ``aux_loss`` metric (0 for dense models).
    """

    def step(state: TrainState, batch):
        if isinstance(batch, tuple):
            tokens, labels = batch
            inputs, targets = tokens, labels
        else:
            inputs, targets = batch[:, :-1], batch[:, 1:]

        def loss_fn(params):
            logits, mods = state.apply_fn(
                {"params": params}, inputs, mutable=["aux_losses"]
            )
            ce_loss = masked_cross_entropy(logits, targets, ignore_id=ignore_id)
            sown = jax.tree_util.tree_leaves(mods.get("aux_losses", {}))
            aux = (
                sum(v.astype(jnp.float32) for v in sown) / len(sown)
                if sown
                else jnp.float32(0.0)
            )
            return ce_loss + aux_loss_weight * aux, (ce_loss, aux)

        (_, (loss, aux)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        state = state.apply_gradients(grads=grads)
        return state, {"loss": loss, "perplexity": jnp.exp(loss), "aux_loss": aux}

    return step


def make_evaluator(module: nn.Module) -> Callable:
    """Build an @model.evaluator-compatible fn: (state, features, labels) -> acc."""

    @jax.jit
    def _acc(params, features, labels):
        logits = module.apply({"params": params}, features)
        return _accuracy(logits, labels)

    def evaluator(state: Any, features: Any, labels: Any) -> float:
        params = state.params if hasattr(state, "params") else state
        return float(_acc(params, jnp.asarray(features), jnp.asarray(labels)))

    return evaluator


def make_predictor(module: nn.Module) -> Callable:
    """Build an @model.predictor-compatible fn: argmax class prediction."""

    @jax.jit
    def _predict(params, features):
        return jnp.argmax(module.apply({"params": params}, features), axis=-1)

    def predictor(state: Any, features: Any) -> Any:
        params = state.params if hasattr(state, "params") else state
        return _predict(params, jnp.asarray(features))

    return predictor
