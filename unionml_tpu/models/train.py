"""Train-state and step-function factories for the model zoo.

The reference leaves training loops to user code (SURVEY.md §3.1: "the hot
loop lives entirely in the user trainer body"). Here the framework supplies
jit-ready ``step(state, batch) -> (state, metrics)`` functions matching the
:meth:`unionml_tpu.model.Model.train_step` contract, so a zoo model trains
with three lines of app code. Loss math runs in fp32 (bf16 params upcast at
the loss) and gradients are computed by a single ``jax.value_and_grad``
program — XLA fuses the whole step into one executable per shape.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax.training import train_state


class TrainState(train_state.TrainState):
    """flax TrainState (params + optax state + apply_fn + step counter)."""


def adamw(learning_rate: float, *, weight_decay: float = 0.0,
          b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
          mu_dtype: Optional[Any] = None):
    """AdamW as an explicit optax chain.

    Mathematically identical to ``optax.adamw``, but ``optax.adamw``
    triggers a ~4x whole-step slowdown under buffer donation on TPU
    (measured on v5e, BERT-base 110M params: 83.5 ms/step vs 20.3 ms for
    this chain — see BASELINE.md); the explicit composition compiles
    clean under donated state.

    ``mu_dtype`` (e.g. ``jnp.bfloat16``) stores the FIRST moment at
    reduced precision — 25% of adam-state memory and its HBM traffic.
    The second moment stays fp32 (bf16's 8-bit mantissa distorts
    ``sqrt(v)`` far more than it does ``m``).
    """
    steps = [optax.scale_by_adam(b1=b1, b2=b2, eps=eps, mu_dtype=mu_dtype)]
    if weight_decay:
        steps.append(optax.add_decayed_weights(weight_decay))
    # scale_by_learning_rate accepts floats AND schedules, like optax.adamw
    steps.append(optax.scale_by_learning_rate(learning_rate))
    return optax.chain(*steps)


def create_train_state(
    module: nn.Module,
    example_input: Any,
    *,
    optimizer: Optional[optax.GradientTransformation] = None,
    learning_rate: float = 1e-3,
    weight_decay: float = 0.0,
    seed: int = 0,
    init_kwargs: Optional[dict] = None,
) -> TrainState:
    """Initialize parameters from an example batch and wrap with optax.

    Default optimizer is :func:`adamw` (the donation-safe chain) — the
    optimizer state duplicates the param pytree twice, so under FSDP the
    same partition rules shard it too (ShardingConfig.state_shardings
    walks the whole TrainState).
    """
    params = module.init(
        jax.random.PRNGKey(seed), example_input, **(init_kwargs or {})
    )["params"]
    tx = optimizer or adamw(learning_rate, weight_decay=weight_decay)
    return TrainState.create(apply_fn=module.apply, params=params, tx=tx)


def _accuracy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    return jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))


def resolve_params(state: Any) -> Any:
    """The full apply-tree behind a state-or-params argument.

    Serving/eval surfaces accept either a bare param tree or any
    TrainState; a :class:`~unionml_tpu.models.lora.LoRATrainState` holds
    only the adapters in ``.params``, so its ``full_params()`` (frozen
    base + adapters) is what ``module.apply`` needs.
    """
    if hasattr(state, "full_params"):
        return state.full_params()
    return state.params if hasattr(state, "params") else state


def _bind_frozen(loss_fn: Callable, state: Any) -> Callable:
    """Adapt a loss over FULL params to a state that differentiates a
    subset: for :class:`~unionml_tpu.models.lora.LoRATrainState` the
    trainable tree (``state.params``, lora adapters) is merged over the
    frozen base inside the loss, so ``value_and_grad`` touches only the
    adapters and the optimizer state stays adapter-sized."""
    frozen = getattr(state, "frozen_params", None)
    if frozen is None:
        return loss_fn
    from unionml_tpu.models.lora import merge_param_trees

    return lambda params, batch: loss_fn(merge_param_trees(frozen, params), batch)


class GradOverlap(NamedTuple):
    """How the accumulation scan should overlap gradient collectives
    with compute (docs/performance.md "Overlapped training").

    ``mode="defer"`` keeps GSPMD's automatic collectives but moves the
    *consumption* of microbatch *i*'s (already-reduced) grads into
    iteration *i+1*'s carry-add, giving XLA's collective pipeliner a
    full microbatch of backward compute to hide each all-reduce behind.
    Works under any mesh (dp/fsdp/tensor/…) and is bitwise identical to
    the serial scan (same adds in the same order, plus one exact +0).

    ``mode="shard_map"`` additionally takes the data-axis all-reduce
    manual: the scan runs inside ``shard_map`` over ``axes`` (params
    replicated across them) and issues a deferred
    :func:`~unionml_tpu.parallel.collectives.bucketed_psum` per
    microbatch — one chunked collective stream XLA's async collectives
    can pipeline. Only valid when every non-``axes`` mesh axis is
    trivial (params must be replicated across ``axes``); loss/grad
    trajectories are bitwise identical to serial for power-of-two
    per-device microbatch rows and device counts (exact fp scaling).
    """

    mode: str
    mesh: Any = None
    axes: Tuple[str, ...] = ()
    #: None = bucketed_psum's own DEFAULT_PSUM_BUCKET_BYTES (no stale
    #: duplicate of the canonical constant here)
    bucket_bytes: Optional[int] = None


_GRAD_OVERLAP: contextvars.ContextVar = contextvars.ContextVar(
    "unionml_grad_overlap", default=None
)


@contextlib.contextmanager
def grad_overlap_scope(overlap: Optional[GradOverlap]):
    """Make ``overlap`` the ambient accumulation strategy: any
    :func:`accumulated_value_and_grad` TRACED inside this scope (i.e.
    any zoo-factory step compiled by a trainer loop running in it)
    adopts it without the step author plumbing a parameter through.
    The trainer loops open this scope for ``overlap_grads=True``; the
    jit cache keys on the ambient overlap so serial and overlapped
    executables never alias."""
    token = _GRAD_OVERLAP.set(overlap)
    try:
        yield overlap
    finally:
        _GRAD_OVERLAP.reset(token)


def current_grad_overlap() -> Optional[GradOverlap]:
    """The ambient :class:`GradOverlap` (None = serial accumulation)."""
    return _GRAD_OVERLAP.get()


def _zeros_like_shapes(tree: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, jnp.float32), tree
    )


def accumulated_value_and_grad(
    loss_fn: Callable,
    params: Any,
    batch: Any,
    *,
    overlap: Optional[GradOverlap] = None,
) -> Tuple[Tuple[jnp.ndarray, Any], Any]:
    """Mean (loss, aux) and grads of ``loss_fn(params, microbatch)`` over
    the leading microbatch axis of ``batch``, via one ``lax.scan``.

    The gradient-accumulation core (SURVEY.md §7 layer 3): ``batch``
    leaves are ``[n_micro, micro_batch, ...]``; each scan step runs one
    microbatch forward+backward and adds into an fp32 grad accumulator,
    so HBM holds one microbatch's activations at a time while the
    *effective* batch is ``n_micro`` times larger. With equal microbatch
    sizes and mean-style losses, the averaged grads equal the one-shot
    big-batch grads up to float summation order (tested). ``aux`` must be
    a pytree of scalars (metrics) — it is averaged the same way.

    ``overlap`` (default: the ambient :func:`grad_overlap_scope`, set by
    ``run_step_trainer(overlap_grads=True)``) restructures the scan so
    gradient collectives overlap the next microbatch's backward — see
    :class:`GradOverlap`; every mode is loss-trajectory-identical to
    the serial scan.
    """
    if overlap is None:
        overlap = _GRAD_OVERLAP.get()
    if overlap is not None and overlap.mode == "shard_map":
        return _shard_map_accumulated(loss_fn, params, batch, overlap)
    defer = overlap is not None and overlap.mode == "defer"
    if overlap is not None and overlap.mode not in ("defer", "shard_map"):
        raise ValueError(
            f"unknown GradOverlap mode {overlap.mode!r}: "
            "expected 'defer' or 'shard_map'"
        )

    vg = jax.value_and_grad(loss_fn, has_aux=True)
    n = jax.tree_util.tree_leaves(batch)[0].shape[0]
    first = jax.tree_util.tree_map(lambda x: x[0], batch)
    # trace-time structure probe: zero accumulators for loss/aux/grads
    (loss_s, aux_s), grad_s = jax.eval_shape(vg, params, first)
    zeros = _zeros_like_shapes

    if defer:
        # deferred consumption: iteration i adds iteration i-1's grads
        # (the `pending` carry) into the accumulator BEFORE computing
        # its own, so the collectives GSPMD attached to microbatch i's
        # grads are not needed until a whole microbatch of backward
        # compute later — the window XLA's collective pipeliner hides
        # them in. Same adds in the same order as the serial scan (plus
        # an exact leading +0): bitwise-identical trajectories.
        def body(carry, microbatch):
            loss_acc, aux_acc, grad_acc, pending = carry
            grad_acc = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), grad_acc, pending
            )
            (loss, aux), grads = vg(params, microbatch)
            loss_acc = loss_acc + loss.astype(jnp.float32)
            aux_acc = jax.tree_util.tree_map(
                lambda a, b: a + jnp.asarray(b, jnp.float32), aux_acc, aux
            )
            return (loss_acc, aux_acc, grad_acc, grads), None

        pending0 = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), grad_s
        )
        (loss, aux, grads, pending), _ = jax.lax.scan(
            body, (zeros(loss_s), zeros(aux_s), zeros(grad_s), pending0),
            batch,
        )
        grads = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(jnp.float32), grads, pending
        )
    else:
        def body(carry, microbatch):
            loss_acc, aux_acc, grad_acc = carry
            (loss, aux), grads = vg(params, microbatch)
            loss_acc = loss_acc + loss.astype(jnp.float32)
            aux_acc = jax.tree_util.tree_map(
                lambda a, b: a + jnp.asarray(b, jnp.float32), aux_acc, aux
            )
            grad_acc = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), grad_acc, grads
            )
            return (loss_acc, aux_acc, grad_acc), None

        (loss, aux, grads), _ = jax.lax.scan(
            body, (zeros(loss_s), zeros(aux_s), zeros(grad_s)), batch
        )
    mean = lambda t: jax.tree_util.tree_map(lambda x: x / n, t)  # noqa: E731
    grads = jax.tree_util.tree_map(
        lambda g, p: (g / n).astype(p.dtype), grads, params
    )
    return (loss / n, mean(aux)), grads


def _shard_map_accumulated(
    loss_fn: Callable, params: Any, batch: Any, overlap: GradOverlap
) -> Tuple[Tuple[jnp.ndarray, Any], Any]:
    """The manual-collective accumulation: scan inside ``shard_map``
    over the batch axes, per-microbatch deferred ``bucketed_psum``.

    Params are replicated across ``overlap.axes`` (the pure-DP layout;
    the trainer only selects this mode when every other mesh axis is
    trivial), each device runs ``loss_fn`` on its local microbatch
    rows, and the data-axis all-reduce of microbatch *i*'s grads is
    issued in iteration *i* but consumed in *i+1* — an explicit,
    chunked collective stream for XLA's async collectives to pipeline
    behind the next backward.
    """
    from jax import lax
    from unionml_tpu.parallel.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from unionml_tpu.parallel.collectives import bucketed_psum

    axes = tuple(overlap.axes)
    if overlap.mesh is None or not axes:
        raise ValueError(
            "GradOverlap(mode='shard_map') needs a mesh and at least one "
            "reduce axis (the batch axes the grads all-reduce over)"
        )
    axis_arg = axes if len(axes) > 1 else axes[0]

    def local(params, batch):
        vg = jax.value_and_grad(loss_fn, has_aux=True)
        n = jax.tree_util.tree_leaves(batch)[0].shape[0]
        first = jax.tree_util.tree_map(lambda x: x[0], batch)
        (loss_s, aux_s), grad_s = jax.eval_shape(vg, params, first)
        zeros = _zeros_like_shapes

        def body(carry, microbatch):
            loss_acc, aux_acc, grad_acc, pending = carry
            # consume the PREVIOUS microbatch's reduced grads first …
            grad_acc = jax.tree_util.tree_map(
                lambda a, g: a + g, grad_acc, pending
            )
            (loss, aux), grads = vg(params, microbatch)
            # … and issue this one's all-reduce, bucketed so the chunks
            # pipeline; its result is not needed until the next
            # iteration's carry-add
            bucket_kw = (
                {} if overlap.bucket_bytes is None
                else {"bucket_bytes": overlap.bucket_bytes}
            )
            reduced = bucketed_psum(
                jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), grads
                ),
                axis_arg, **bucket_kw,
            )
            loss_acc = loss_acc + lax.pmean(
                loss.astype(jnp.float32), axis_arg
            )
            aux_acc = jax.tree_util.tree_map(
                lambda a, b: a + lax.pmean(
                    jnp.asarray(b, jnp.float32), axis_arg
                ),
                aux_acc, aux,
            )
            return (loss_acc, aux_acc, grad_acc, reduced), None

        (loss, aux, grads, pending), _ = jax.lax.scan(
            body,
            (zeros(loss_s), zeros(aux_s), zeros(grad_s), zeros(grad_s)),
            batch,
        )
        grads = jax.tree_util.tree_map(lambda a, g: a + g, grads, pending)
        ndev = lax.psum(1, axis_arg)
        mean = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: x / n, t
        )
        # /(n*ndev) in ONE division: ndev is a power of two on real
        # meshes, so the extra scale vs the serial path's /n is exact
        grads = jax.tree_util.tree_map(
            lambda g, p: (g / (n * ndev)).astype(p.dtype), grads, params
        )
        return (loss / n, mean(aux)), grads

    fn = shard_map(
        local, overlap.mesh,
        in_specs=(P(), P(None, axes if len(axes) > 1 else axes[0])),
        out_specs=((P(), P()), P()),
        check_rep=False,
    )
    return fn(params, batch)


def masked_cross_entropy(
    logits: jnp.ndarray, targets: jnp.ndarray, *, ignore_id: int = -100
) -> jnp.ndarray:
    """Mean CE over positions where ``targets != ignore_id`` (fp32 math).

    Shared by the serial :func:`lm_step` and the pipelined trainer
    (models/pipeline_lm.py) so their losses cannot drift apart.
    """
    logits = logits.astype(jnp.float32)
    mask = (targets != ignore_id).astype(jnp.float32)
    safe = jnp.where(targets == ignore_id, 0, targets)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, safe)
    return (ce * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def classification_step(module: nn.Module, *, accumulate_steps: int = 1) -> Callable:
    """softmax-CE step for (features, int_labels) batches (MLP/ViT/BERT-cls).

    ``accumulate_steps > 1``: the step expects batches with a leading
    microbatch axis (``[n_micro, micro_batch, ...]`` — the trainer's
    ``accumulate_steps`` feeds this shape) and applies ONE optimizer
    update from the grad mean over the scan (gradient accumulation).
    """

    def loss_fn(params, microbatch):
        features, labels = microbatch
        logits = module.apply({"params": params}, features)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels
        ).mean()
        return loss, {"accuracy": _accuracy(logits, labels)}

    def step(state: TrainState, batch: Tuple[Any, Any]):
        bound = _bind_frozen(loss_fn, state)
        if accumulate_steps > 1:
            (loss, aux), grads = accumulated_value_and_grad(
                bound, state.params, batch
            )
        else:
            (loss, aux), grads = jax.value_and_grad(bound, has_aux=True)(
                state.params, batch
            )
        state = state.apply_gradients(grads=grads)
        return state, {"loss": loss, "accuracy": aux["accuracy"]}

    return step


def lm_step(
    module: nn.Module,
    *,
    ignore_id: int = -100,
    aux_loss_weight: float = 0.01,
    accumulate_steps: int = 1,
) -> Callable:
    """Next-token LM step: batch is token ids [B, S]; loss over shifted pairs.

    Also accepts ``(tokens, labels)`` for masked-LM/fine-tune batches where
    labels carry ``ignore_id`` at unsupervised positions.

    MoE modules sow per-layer load-balancing losses into the
    ``aux_losses`` collection (ops/moe.py); their layer-mean is added to
    the CE loss scaled by ``aux_loss_weight`` and reported as the
    ``aux_loss`` metric (0 for dense models).

    ``accumulate_steps > 1``: gradient accumulation — batches carry a
    leading microbatch axis ([n_micro, micro_batch, S]), grads are
    scan-accumulated in fp32, and the optimizer updates once. This is
    the HBM-bound long-context knob: the 16k-context leg runs microbatch
    1 per device; accumulation restores the effective batch without the
    activation memory (BASELINE.md long-context table).
    """

    def loss_fn(params, microbatch):
        if isinstance(microbatch, tuple):
            inputs, targets = microbatch
        else:
            inputs, targets = microbatch[:, :-1], microbatch[:, 1:]
        logits, mods = module.apply(
            {"params": params}, inputs, mutable=["aux_losses"]
        )
        ce_loss = masked_cross_entropy(logits, targets, ignore_id=ignore_id)
        sown = jax.tree_util.tree_leaves(mods.get("aux_losses", {}))
        aux = (
            sum(v.astype(jnp.float32) for v in sown) / len(sown)
            if sown
            else jnp.float32(0.0)
        )
        return ce_loss + aux_loss_weight * aux, {"ce": ce_loss, "aux": aux}

    def step(state: TrainState, batch):
        bound = _bind_frozen(loss_fn, state)
        if accumulate_steps > 1:
            (_, aux), grads = accumulated_value_and_grad(
                bound, state.params, batch
            )
        else:
            (_, aux), grads = jax.value_and_grad(bound, has_aux=True)(
                state.params, batch
            )
        state = state.apply_gradients(grads=grads)
        loss, aux_loss = aux["ce"], aux["aux"]
        return state, {"loss": loss, "perplexity": jnp.exp(loss), "aux_loss": aux_loss}

    return step


def make_evaluator(module: nn.Module) -> Callable:
    """Build an @model.evaluator-compatible fn: (state, features, labels) -> acc."""

    @jax.jit
    def _acc(params, features, labels):
        logits = module.apply({"params": params}, features)
        return _accuracy(logits, labels)

    def evaluator(state: Any, features: Any, labels: Any) -> float:
        return float(_acc(resolve_params(state), jnp.asarray(features), jnp.asarray(labels)))

    return evaluator


def make_predictor(module: nn.Module) -> Callable:
    """Build an @model.predictor-compatible fn: argmax class prediction."""

    @jax.jit
    def _predict(params, features):
        return jnp.argmax(module.apply({"params": params}, features), axis=-1)

    def predictor(state: Any, features: Any) -> Any:
        return _predict(resolve_params(state), jnp.asarray(features))

    return predictor
