"""LoRA / QLoRA parameter-efficient fine-tuning.

No reference counterpart (the reference delegates training entirely to
user sklearn/torch/keras code — reference: unionml/model.py:425-440 just
calls the user's trainer). On TPU the motivating config is the serving
flagship run in reverse: **fine-tune Llama-3-8B on ONE v5e chip**, which
is impossible with full fine-tuning (bf16 params + fp32 master + adam
m/v ≈ 96 GB) but feasible QLoRA-style: the frozen base stays int8
(~8.6 GB, the same weights the serving path streams), and only rank-r
adapters (~0.1% of params) carry gradients and optimizer state.

Design:

- :class:`LoRADenseGeneral` — drop-in for the dense factory in
  :mod:`unionml_tpu.models.layers`: it creates the SAME base parameters
  at the SAME paths as the layer it replaces (fp ``kernel`` [+ ``bias``]
  or int8 ``kernel_q``+``scale``), so existing trained/quantized
  checkpoints load unchanged, plus ``lora_a`` [K, r] / ``lora_b`` [r, N]
  adapters. Forward adds ``(x @ A) @ B * (alpha / r)`` — two skinny
  matmuls, never materializing the [K, N] delta. ``lora_b`` initializes
  to zeros, so step 0 output is bit-identical to the base model.
- :func:`split_lora_params` / :func:`merge_param_trees` — partition a
  param tree into (adapters, frozen base) and re-union them; the train
  step differentiates the adapter tree only, so optimizer state is
  adapter-sized.
- :class:`LoRATrainState` / :func:`create_lora_train_state` — a
  TrainState whose ``params`` are the adapters and whose frozen base
  rides along as a non-differentiated field (donated and device-resident
  like everything else under ``compile_step``).
- :func:`merge_lora` — fold adapters into the base kernels for serving
  (fp exactly; int8 by dequantize → add → requantize), returning a tree
  the ``lora_rank=0`` config loads, so the serving path (bucketed
  predictor, continuous engine, speculative) needs no LoRA awareness.

Sharding: under tensor parallelism the skinny adapter matmuls follow
their base kernel's layout — ``lora_b`` shards N wherever the base
kernel shards N (q/k/v/gate/up), ``lora_a`` shards K wherever the base
shards K (o/down) — one psum per block, unchanged from the Megatron
layout (:data:`LLAMA_LORA_PARTITION_RULES`). The rank axis is never
sharded (r ~ 8-64 is far below useful shard sizes).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from flax import struct

from unionml_tpu.models.train import TrainState, adamw
from unionml_tpu.parallel.sharding import PartitionRule

Dtype = Any

LORA_PARAM_NAMES = ("lora_a", "lora_b")


class LoRADenseGeneral(nn.Module):
    """DenseGeneral with a low-rank trainable delta on a frozen-able base.

    Parameter paths match the module this factory replaces (see
    :func:`unionml_tpu.models.layers.make_dense`): fp base stores
    ``kernel`` with DenseGeneral's geometry ``[*contracted, *features]``
    (plus ``bias`` when ``use_bias``); quantized base stores ``kernel_q``
    int8 ``[K, N]`` + ``scale`` fp32 ``[N]``. Adapters are always 2D:
    ``lora_a`` ``[K, r]`` (fan-in-scaled normal init), ``lora_b``
    ``[r, N]`` (zeros — the delta starts at 0).
    """

    features: Union[int, Sequence[int]]
    lora_rank: int
    lora_alpha: float = 16.0
    axis: Union[int, Sequence[int]] = -1
    quantized: bool = False
    use_bias: bool = False
    dtype: Dtype = jnp.bfloat16
    param_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.lora_rank <= 0:
            raise ValueError("LoRADenseGeneral needs lora_rank >= 1")
        axes = (self.axis,) if isinstance(self.axis, int) else tuple(self.axis)
        axes = tuple(a % x.ndim for a in axes)
        feats = (self.features,) if isinstance(self.features, int) else tuple(self.features)
        contracted = tuple(x.shape[a] for a in axes)
        k = int(np.prod(contracted))
        n = int(np.prod(feats))

        # flatten x's contracted dims once; base and adapter share it
        batch_axes = tuple(i for i in range(x.ndim) if i not in axes)
        xt = x.transpose(*batch_axes, *axes).reshape(
            tuple(x.shape[i] for i in batch_axes) + (k,)
        )

        if self.quantized:
            assert not self.use_bias, "quantized dense layers are bias-free"
            kernel_q = self.param("kernel_q", nn.initializers.zeros, (k, n), jnp.int8)
            scale = self.param("scale", nn.initializers.ones, (n,), jnp.float32)
            y = jax.lax.dot_general(
                xt.astype(self.dtype), kernel_q.astype(self.dtype),
                (((xt.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            y = y * scale
        else:
            # match flax DenseGeneral's init exactly: fan-in is computed on
            # the FLATTENED [K, N] shape (a direct lecun_normal over the
            # multi-dim (contracted..., feats...) shape would mis-read
            # fan-in as the second-to-last dim, under-scaling q/k/v by
            # sqrt(num_heads))
            def kernel_init(rng, shape, dtype):
                flat = nn.initializers.lecun_normal()(rng, (k, n), dtype)
                return flat.reshape(shape)

            kernel = self.param(
                "kernel", kernel_init, contracted + feats, self.param_dtype
            )
            w = kernel.reshape(k, n).astype(self.dtype)
            y = jax.lax.dot_general(
                xt.astype(self.dtype), w,
                (((xt.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if self.use_bias:
                bias = self.param("bias", nn.initializers.zeros, (n,), self.param_dtype)
                y = y + bias.astype(jnp.float32)

        # rank-r delta: fan-in-scaled A, zero B — identity at init. The
        # alpha/r scale rides the tiny [r, N] factor, not the activations.
        lora_a = self.param(
            "lora_a",
            nn.initializers.variance_scaling(1.0, "fan_in", "normal"),
            (k, self.lora_rank), self.param_dtype,
        )
        lora_b = self.param(
            "lora_b", nn.initializers.zeros, (self.lora_rank, n), self.param_dtype
        )
        scale_b = (lora_b * (self.lora_alpha / self.lora_rank)).astype(self.dtype)
        delta = jax.lax.dot_general(
            jax.lax.dot_general(
                xt.astype(self.dtype), lora_a.astype(self.dtype),
                (((xt.ndim - 1,), (0,)), ((), ())),
            ),
            scale_b,
            (((xt.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        y = (y + delta).astype(self.dtype)
        return y.reshape(y.shape[:-1] + feats)


# -- param-tree surgery -------------------------------------------------- #


def split_lora_params(params: Any) -> Tuple[Any, Any]:
    """Partition a param tree into ``(adapters, base)``.

    ``adapters`` keeps only ``lora_a``/``lora_b`` leaves (preserving their
    nesting); ``base`` keeps everything else. Either side omits dict nodes
    that end up empty, so ``adapters`` is exactly the trainable tree the
    optimizer sees.
    """

    from collections.abc import Mapping

    def walk(tree):
        # Mapping, not dict: flax FrozenDict checkpoint trees must walk
        # like dicts — treating them as leaves would return zero adapters
        # here and silently drop base keys in merge_param_trees
        if not isinstance(tree, Mapping):
            return None, tree
        lora, base = {}, {}
        for key, value in tree.items():
            if key in LORA_PARAM_NAMES:
                lora[key] = value
            elif isinstance(value, Mapping):
                sub_lora, sub_base = walk(value)
                if sub_lora:
                    lora[key] = sub_lora
                if sub_base:
                    base[key] = sub_base
            else:
                base[key] = value
        return lora, base

    lora, base = walk(params)
    return lora or {}, base or {}


def merge_param_trees(base: Any, overlay: Any) -> Any:
    """Structural union of two param trees (overlay wins on key clashes).

    The train step rebuilds the full apply tree as
    ``merge_param_trees(frozen_base, adapter_params)`` inside the loss, so
    gradients flow only to the overlay's leaves.
    """
    from collections.abc import Mapping

    if not isinstance(base, Mapping) or not isinstance(overlay, Mapping):
        return overlay
    out = dict(base)
    for key, value in overlay.items():
        out[key] = merge_param_trees(base.get(key), value) if key in base else value
    return out


# -- training ------------------------------------------------------------ #


class LoRATrainState(TrainState):
    """TrainState over the adapter tree, with the frozen base riding along.

    ``params`` (and therefore the optimizer state) hold ONLY lora leaves;
    ``frozen_params`` is the base tree, donated and device-resident but
    never differentiated. ``full_params()`` is what ``module.apply``
    consumes.
    """

    frozen_params: Any = struct.field(pytree_node=True, default=None)

    def full_params(self) -> Any:
        return merge_param_trees(self.frozen_params, self.params)


def create_lora_train_state(
    module: nn.Module,
    example_input: Any,
    *,
    base_params: Optional[Any] = None,
    optimizer: Optional[optax.GradientTransformation] = None,
    learning_rate: float = 1e-4,
    weight_decay: float = 0.0,
    seed: int = 0,
    init_kwargs: Optional[dict] = None,
) -> LoRATrainState:
    """Initialize a LoRA fine-tune state.

    ``module`` must be configured with ``lora_rank > 0`` (e.g.
    ``LlamaConfig(lora_rank=16)``). Fresh adapters come from ``init``;
    the frozen base is ``base_params`` when given (a trained or
    :func:`~unionml_tpu.models.quantization.quantize_params`-converted
    tree whose structure must match the module's non-lora params), else
    the init's own base (from-scratch smoke tests).
    """
    # never materialize the base tree when one was supplied: for the
    # motivating config (8B base already resident on a 16 GB chip) a full
    # module.init would allocate a second base-sized tree just to throw it
    # away. eval_shape gives the structure/shapes for free; only the tiny
    # adapters need concrete initialization.
    shapes = jax.eval_shape(
        lambda rng: module.init(rng, example_input, **(init_kwargs or {})),
        jax.random.PRNGKey(seed),
    )["params"]
    lora_shapes, base_shapes = split_lora_params(shapes)
    if not lora_shapes:
        raise ValueError(
            "module has no lora_a/lora_b parameters — set lora_rank > 0 "
            "on its config before building a LoRA train state"
        )
    if base_params is None:
        full = module.init(
            jax.random.PRNGKey(seed), example_input, **(init_kwargs or {})
        )["params"]
        adapters, frozen = split_lora_params(full)
    else:
        base_lora, base_only = split_lora_params(base_params)
        if base_lora:
            raise ValueError(
                "base_params already contain lora adapters; merge or strip "
                "them first (merge_lora / split_lora_params)"
            )
        want = jax.tree_util.tree_structure(base_shapes)
        got = jax.tree_util.tree_structure(base_only)
        if want != got:
            raise ValueError(
                "base_params structure does not match the module's frozen "
                f"parameters:\n  expected {want}\n  got      {got}"
            )
        jax.tree_util.tree_map(
            lambda spec, leaf: None
            if tuple(spec.shape) == tuple(jnp.shape(leaf))
            else (_ for _ in ()).throw(
                ValueError(
                    f"base_params leaf shape {jnp.shape(leaf)} does not "
                    f"match the module's expected {tuple(spec.shape)}"
                )
            ),
            base_shapes, base_only,
        )
        frozen = base_only
        # adapters: same distributions the module uses (lora_a fan-in
        # normal, lora_b zeros), drawn per-path from the seed
        root = jax.random.PRNGKey(seed)

        def init_adapter(path, spec):
            import zlib

            name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
            if name == "lora_b":
                return jnp.zeros(spec.shape, spec.dtype)
            # crc32 of the path: deterministic across processes (unlike
            # hash()), unique enough per adapter
            key = jax.random.fold_in(
                root,
                zlib.crc32(jax.tree_util.keystr(path).encode()) & 0x7FFFFFFF,
            )
            fan_in = spec.shape[0]
            draw = jax.random.normal(key, spec.shape, jnp.float32)
            return (draw / jnp.sqrt(jnp.float32(fan_in))).astype(spec.dtype)

        adapters = jax.tree_util.tree_map_with_path(init_adapter, lora_shapes)
    tx = optimizer or adamw(learning_rate, weight_decay=weight_decay)
    return LoRATrainState.create(
        apply_fn=module.apply, params=adapters, tx=tx, frozen_params=frozen
    )


# -- serving-time merge -------------------------------------------------- #


def merge_lora(params: Any, *, alpha: float) -> Any:
    """Fold adapters into base kernels; returns a lora-free tree.

    The result loads into the SAME architecture with ``lora_rank=0``
    (geometry unchanged), so every serving surface — bucketed predictor,
    continuous engine, speculative target/draft — consumes fine-tuned
    weights with zero LoRA plumbing. fp kernels merge exactly
    (``W += (A @ B) * alpha/r`` in fp32, reshaped to the kernel's
    DenseGeneral geometry); int8 kernels dequantize per output channel,
    add the delta, and requantize (error bounded by the int8 grid, tested
    against the unmerged forward).

    ``alpha`` is REQUIRED and must be the config's ``lora_alpha`` the
    adapters were trained with (pass ``cfg.lora_alpha``): the rank is
    read off the adapter shapes, but alpha is not recoverable from the
    tree — a defaulted wrong value would fold every delta in at the
    wrong strength and produce a structurally valid, numerically wrong
    checkpoint with no error.
    """

    def walk(tree):
        if not isinstance(tree, dict):
            return tree
        if "lora_a" in tree and "lora_b" in tree:
            a = jnp.asarray(tree["lora_a"], jnp.float32)
            b = jnp.asarray(tree["lora_b"], jnp.float32)
            rank = a.shape[-1]
            delta = (a @ b) * (alpha / rank)  # [K, N]
            out = {
                key: value
                for key, value in tree.items()
                if key not in LORA_PARAM_NAMES
            }
            if "kernel" in tree:
                kernel = jnp.asarray(tree["kernel"])
                out["kernel"] = (
                    kernel.astype(jnp.float32)
                    + delta.reshape(kernel.shape)
                ).astype(kernel.dtype)
            elif "kernel_q" in tree:
                from unionml_tpu.models.quantization import _quantize_kernel_2d

                w = tree["kernel_q"].astype(jnp.float32) * jnp.asarray(
                    tree["scale"], jnp.float32
                )
                q, scale = _quantize_kernel_2d(w + delta)
                out["kernel_q"], out["scale"] = q, scale
            else:
                raise ValueError(
                    "lora adapters found beside neither 'kernel' nor "
                    f"'kernel_q' (keys: {sorted(tree)})"
                )
            return out
        return {key: walk(value) for key, value in tree.items()}

    return walk(params)


# -- tensor-parallel layout ---------------------------------------------- #

# adapters follow their base kernel's Megatron layout: B shards N where the
# base shards N (column-parallel q/k/v/gate/up), A shards K where the base
# shards K (row-parallel o/down). The rank dim stays whole. lm_head and the
# embedding carry no adapters (llama.py builds them lora-free).
LORA_PARTITION_RULES = (
    PartitionRule(r"attn/(q|k|v)/lora_b$", (None, "tensor")),
    PartitionRule(r"attn/(q|k|v)/lora_a$", (None, None)),
    PartitionRule(r"attn/o/lora_a$", ("tensor", None)),
    PartitionRule(r"attn/o/lora_b$", (None, None)),
    PartitionRule(r"mlp/(gate|up)/lora_b$", (None, "tensor")),
    PartitionRule(r"mlp/(gate|up)/lora_a$", (None, None)),
    PartitionRule(r"mlp/down/lora_a$", ("tensor", None)),
    PartitionRule(r"mlp/down/lora_b$", (None, None)),
)
