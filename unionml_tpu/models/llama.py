"""Llama-3-style decoder — the serving flagship (BASELINE.json config #5,
"Llama-3-8B FastAPI predictor serving (on-device batching on TPU)").

Architecture: RMSNorm, rotary embeddings (theta=500k), grouped-query
attention, SwiGLU MLP, untied LM head. Two execution modes:

- **full-sequence** (training / prefill): causal attention via the op
  family (xla / blockwise / flash Pallas / ring / ulysses — config knob);
- **cached decode**: a functional KV cache (pytree of per-layer (k, v)
  buffers, static max_len) threaded through ``__call__`` so the serving
  batcher jit-compiles ONE decode program with a dynamic fill index — no
  recompilation per token (SURVEY.md §7 hard part (e): bucketed shapes).

TP partition rules shard heads (q/k/v out-features, o in-features) and
SwiGLU hidden over the ``tensor`` axis; the embedding and LM head shard
vocab. FSDP fallback covers everything else.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Tuple

import jax.numpy as jnp
from flax import linen as nn

from unionml_tpu.models.layers import Attention, MlpBlock, RMSNorm, make_dense
from unionml_tpu.ops.moe import MoEMlp
from unionml_tpu.parallel.sharding import PartitionRule

Cache = Tuple[Tuple[jnp.ndarray, jnp.ndarray], ...]  # per-layer (k, v)


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    hidden_dim: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    mlp_dim: int = 14_336
    rope_theta: float = 500_000.0
    # llama3-type long-context RoPE rescale, as the hashable tuple
    # (factor, low_freq_factor, high_freq_factor, original_max_len) —
    # what HF Llama-3.1/3.2 config.json carries as `rope_scaling`
    # (models/convert.py maps it; ops in models/layers.py)
    rope_scaling: Optional[Tuple[float, float, float, int]] = None
    norm_eps: float = 1e-5  # HF `rms_norm_eps` (1e-6 for Llama-2-era)
    max_len: int = 8192
    attn_impl: str = "xla"
    # attention impl for FULL prefills (empty cache, no prefix, no lead
    # chunks): "flash" runs the Pallas flash kernel over the fresh k/v —
    # no [B, H, S, max_len] score buffer, the long-prompt monolithic-
    # prefill memory/speed lever (see Attention.prefill_impl). "cached"
    # keeps the masked cached-attention path everywhere.
    prefill_impl: str = "cached"
    # decode attention over a BLOCK-PAGED KV pool (the engine's paged
    # mode; consulted only when block_table= is passed): "reference" =
    # jnp.take gather (bit-identical to the contiguous path — the
    # CPU/parity anchor), "pallas" = the scalar-prefetch gather kernel,
    # "auto" = pallas on TPU / reference elsewhere.
    paged_impl: str = "auto"
    # "fused" = Pallas RMSNorm kernel pair (ops/fused_norm.py)
    norm_impl: str = "xla"
    sequence_axis: Optional[str] = None
    quantized: bool = False  # weight-only quantized matmuls (serving path)
    # 8 = int8 (the default serving artifact); 4 = packed-int4 via the
    # Pallas decode kernel (ops/int4_matmul.py) — halves decode weight
    # traffic again. LoRA/QLoRA and MoE experts stay int8.
    weight_bits: int = 8
    # int4 quality/parallelism knobs (weight_bits=4 only). int4_group>0:
    # group-wise scales [K/g, N] (quantize_params(group_size=...) must
    # match). int4_tp>1: the tensor degree the packing tiles must
    # survive (quantize_params(tensor=...) must match) — serving at any
    # DIVISOR of int4_tp stays slab-aligned; a finer split does not.
    int4_group: int = 0
    int4_tp: int = 1
    remat: bool = False  # gradient checkpointing per block (long-context training)
    # mixture-of-experts MLPs (0 = dense). Experts shard over the mesh's
    # `expert` axis via LLAMA_MOE_PARTITION_RULES; GSPMD inserts the
    # dispatch collectives (see ops/moe.py for the explicit all_to_all op).
    num_experts: int = 0
    num_selected: int = 2
    # LoRA fine-tuning: rank-r adapters on attention q/k/v/o and dense-MLP
    # gate/up/down (models/lora.py). With quantized=True this is the QLoRA
    # configuration: int8 frozen base + bf16-computed fp32 adapters — the
    # single-chip 8B fine-tune path. MoE expert weights are NOT adapted
    # (MoEMlp has no lora path; attention adapters still apply).
    lora_rank: int = 0
    lora_alpha: float = 16.0
    # int8 KV cache (generation paths): halves cache HBM — the binding
    # constraint for long contexts and engine slot counts (an 8B 8k-ctx
    # batch-8 bf16 cache is ~8.6 GB, rivaling the int8 weights) — with
    # per-(position, kv_head) scales. init_cache builds the quantized
    # layout; Attention infers it from the cache structure.
    kv_quant: bool = False
    dtype: str = "bfloat16"

    def __post_init__(self):
        if self.num_experts:
            if not 1 <= self.num_selected <= self.num_experts:
                raise ValueError(
                    f"num_selected={self.num_selected} must be in "
                    f"[1, num_experts={self.num_experts}]"
                )

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def mixtral_8x7b() -> "LlamaConfig":
        """Mixtral-8x7B geometry: Llama blocks + 8-expert top-2 MoE MLPs."""
        return LlamaConfig(
            vocab_size=32_000, hidden_dim=4096, num_layers=32, num_heads=32,
            num_kv_heads=8, mlp_dim=14_336, rope_theta=1e6, max_len=32_768,
            num_experts=8, num_selected=2,
        )

    @staticmethod
    def tiny(vocab_size: int = 512, **overrides) -> "LlamaConfig":
        kwargs = dict(
            vocab_size=vocab_size, hidden_dim=64, num_layers=2, num_heads=4,
            num_kv_heads=2, mlp_dim=128, max_len=256, rope_theta=10_000.0,
        )
        kwargs.update(overrides)
        return LlamaConfig(**kwargs)

    @property
    def head_dim(self) -> int:
        return self.hidden_dim // self.num_heads


class LlamaBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, *, positions=None, cache=None, cache_index=None,
                 kv_mask=None, block_table=None, full_prefill=False):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        attn = Attention(
            num_heads=cfg.num_heads,
            num_kv_heads=cfg.num_kv_heads,
            head_dim=cfg.head_dim,
            rope=True,
            rope_theta=cfg.rope_theta,
            rope_scaling=cfg.rope_scaling,
            causal=True,
            attn_impl=cfg.attn_impl,
            prefill_impl=cfg.prefill_impl,
            paged_impl=cfg.paged_impl,
            sequence_axis=cfg.sequence_axis,
            quantized=cfg.quantized,
            weight_bits=cfg.weight_bits,
            int4_group=cfg.int4_group,
            int4_tp=cfg.int4_tp,
            lora_rank=cfg.lora_rank,
            lora_alpha=cfg.lora_alpha,
            dtype=dtype,
            name="attn",
        )
        h = RMSNorm(eps=cfg.norm_eps, dtype=dtype, impl=cfg.norm_impl, name="attn_norm")(x)
        if cache is not None:
            a, new_cache = attn(
                h, positions=positions, cache=cache, cache_index=cache_index,
                kv_mask=kv_mask, block_table=block_table,
                full_prefill=full_prefill,
            )
        else:
            if kv_mask is not None:
                # the non-cache attention path has no mask plumbing; silently
                # ignoring the mask would attend padded tokens
                raise ValueError(
                    "kv_mask requires a KV cache (generation path); for "
                    "cache-free padded batches use segment_ids/bias on the "
                    "xla attention op instead"
                )
            a, new_cache = attn(h, positions=positions), None
        x = x + a
        h = RMSNorm(eps=cfg.norm_eps, dtype=dtype, impl=cfg.norm_impl, name="mlp_norm")(x)
        if cfg.num_experts:
            mlp_out, aux = MoEMlp(
                num_experts=cfg.num_experts, num_selected=cfg.num_selected,
                hidden_dim=cfg.mlp_dim, model_dim=cfg.hidden_dim,
                quantized=cfg.quantized, dtype=dtype, name="moe",
            )(h)
            # collected by lm_step via mutable=["aux_losses"] and added to
            # the CE loss with a load-balancing weight
            self.sow("aux_losses", "moe_load_balance", aux)
            x = x + mlp_out
        else:
            x = x + MlpBlock(
                hidden_dim=cfg.mlp_dim, gated=True, quantized=cfg.quantized,
                weight_bits=cfg.weight_bits,
                int4_group=cfg.int4_group, int4_tp=cfg.int4_tp,
                lora_rank=cfg.lora_rank, lora_alpha=cfg.lora_alpha,
                dtype=dtype, name="mlp",
            )(h)
        return x, new_cache


class Llama(nn.Module):
    config: LlamaConfig = field(default_factory=LlamaConfig)

    @nn.compact
    def __call__(
        self,
        tokens: jnp.ndarray,
        *,
        positions: Optional[jnp.ndarray] = None,
        cache: Optional[Cache] = None,
        cache_index: Optional[jnp.ndarray] = None,
        kv_mask: Optional[jnp.ndarray] = None,
        block_table: Optional[jnp.ndarray] = None,
        logit_index: Optional[jnp.ndarray] = None,
        full_prefill: bool = False,
    ):
        """logits [B,S,V]; with ``cache`` returns (logits, new_cache).

        ``block_table``: int32 [B, table_width] — marks ``cache`` as a
        block-paged pool (per layer [num_blocks, block, kv_heads,
        head_dim]) addressed through the table; decode steps only
        (``seq == 1``, vector ``cache_index``). See
        :class:`~unionml_tpu.models.layers.Attention`.

        ``kv_mask``: bool (batch, max_len) — False cache slots are never
        attended to (left-padded prompts in generation).
        ``full_prefill``: static caller promise that this cached call
        covers the entire visible history (empty cache, index 0, no
        prefix) — lets ``cfg.prefill_impl == "flash"`` run attention over
        the fresh k/v alone (see Attention.full_prefill).
        ``logit_index``: optional int [B] — compute the LM head for only
        that position per row (returned logits are [B, 1, V]). Generation
        needs one next-token distribution, but the full-sequence head on
        a long prefill materializes [B, S, vocab] fp32 — 33 GB at 8B,
        batch 8, 8k context — so serving paths pass the last real
        position instead.
        """
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        x = nn.Embed(cfg.vocab_size, cfg.hidden_dim, dtype=dtype, name="embed")(tokens)
        if positions is None and cache_index is not None:
            index = jnp.asarray(cache_index)
            if index.ndim == 1:  # per-row fill positions (slot decode)
                index = index[:, None]
            positions = index + jnp.arange(tokens.shape[1])[None, :]
        new_cache = []
        # remat: recompute block activations in the backward instead of
        # storing them — O(sqrt)-style memory for long-context training.
        # Decode (cache path) never remats: there is no backward.
        block_cls = (
            nn.remat(LlamaBlock, static_argnums=())
            if cfg.remat and cache is None
            else LlamaBlock
        )
        for i in range(cfg.num_layers):
            layer_cache = cache[i] if cache is not None else None
            x, c = block_cls(cfg, name=f"block_{i}")(
                x, positions=positions, cache=layer_cache, cache_index=cache_index,
                kv_mask=kv_mask, block_table=block_table,
                full_prefill=full_prefill,
            )
            new_cache.append(c)
        if logit_index is not None:
            idx = jnp.asarray(logit_index)
            x = x[jnp.arange(x.shape[0]), idx][:, None, :]  # [B, 1, D]
        x = RMSNorm(eps=cfg.norm_eps, dtype=dtype, impl=cfg.norm_impl, name="final_norm")(x)
        logits = make_dense(
            quantized=cfg.quantized, features=cfg.vocab_size,
            weight_bits=cfg.weight_bits,
            # lm_head is ROW-parallel under int4 TP (kernel_p K-sharded,
            # partial logits psum'd by GSPMD): 8B's 128256 channels have
            # no power-of-two tile split, but K=hidden always divides —
            # so shards stays 1 and the packing tile ignores TP
            int4_group=cfg.int4_group,
            dtype=jnp.float32, name="lm_head",
        )(x.astype(jnp.float32))
        if cache is not None:
            return logits, tuple(new_cache)
        return logits


def init_cache(
    config: LlamaConfig, batch: int, max_len: Optional[int] = None, dtype: Any = jnp.bfloat16
) -> Cache:
    """Zero-filled KV cache: per-layer (k, v) of [B, max_len, kv_heads, head_dim].

    With ``config.kv_quant`` each layer is instead
    ``(k_q int8, v_q int8, k_scale fp32 [B, max_len, kv_heads], v_scale)``
    — half the HBM of the bf16 form (int8 bytes + 1/32 scale overhead).
    """
    max_len = max_len or config.max_len
    shape = (batch, max_len, config.num_kv_heads, config.head_dim)
    if config.kv_quant:
        if dtype != jnp.bfloat16:
            # the dtype arg governs the bf16 cache form only; silently
            # dropping an explicit request would be a trap
            raise ValueError(
                f"kv_quant caches are int8 + fp32 scales; dtype={dtype} "
                "cannot apply (drop the dtype argument or kv_quant)"
            )
        q = jnp.zeros(shape, jnp.int8)
        s = jnp.ones(shape[:-1], jnp.float32)
        return tuple((q, q, s, s) for _ in range(config.num_layers))
    zeros = jnp.zeros(shape, dtype)
    return tuple((zeros, zeros) for _ in range(config.num_layers))


LLAMA_PARTITION_RULES = (
    # `$`-anchored so `kernel` never matches the quantized `kernel_q` params
    PartitionRule(r"attn/(q|k|v)/kernel$", (None, "tensor", None)),
    PartitionRule(r"attn/o/kernel$", ("tensor", None, None)),
    PartitionRule(r"mlp/(gate|up)/kernel$", (None, "tensor")),
    PartitionRule(r"mlp/down/kernel$", ("tensor", None)),
    PartitionRule(r"embed/embedding$", ("tensor", None)),
    PartitionRule(r"lm_head/kernel$", (None, "tensor")),
)

# int8 serving (LlamaConfig.quantized=True): kernels are 2D [K, N] with a
# per-output-channel scale [N]. Megatron layout carries over: qkv/gate/up/
# lm_head shard N (their scales shard with it); o/down shard K (their
# scales are replicated since N is unsharded).
LLAMA_QUANT_PARTITION_RULES = LLAMA_PARTITION_RULES + (
    PartitionRule(r"attn/(q|k|v)/kernel_q$", (None, "tensor")),
    PartitionRule(r"attn/(q|k|v)/scale$", ("tensor",)),
    PartitionRule(r"attn/o/kernel_q$", ("tensor", None)),
    PartitionRule(r"mlp/(gate|up)/kernel_q$", (None, "tensor")),
    PartitionRule(r"mlp/(gate|up)/scale$", ("tensor",)),
    PartitionRule(r"mlp/down/kernel_q$", ("tensor", None)),
    PartitionRule(r"lm_head/kernel_q$", (None, "tensor")),
    PartitionRule(r"lm_head/scale$", ("tensor",)),
)

# LoRA fine-tune configs (lora_rank > 0): adapter factors follow their
# base kernel's Megatron layout (rules in models/lora.py); the union
# covers fp and QLoRA (int8 base) alike.
from unionml_tpu.models.lora import LORA_PARTITION_RULES  # noqa: E402

LLAMA_LORA_PARTITION_RULES = LORA_PARTITION_RULES + LLAMA_QUANT_PARTITION_RULES

# packed-int4 serving (weight_bits=4): kernel_p is [K, N/2] (packed
# output channels). Megatron layout as int8 for q/k/v/gate/up (N
# sharded — a `tensor` shard of the packed/scale columns is
# self-consistent because the packing tile divides the per-device
# channel count when the tree is quantized with tensor=int4_tp; validate
# with assert_int4_tp_compatible) and o/down (K sharded). The lm_head is
# ROW-parallel (K sharded): 8B's 128256 channels have no power-of-two
# tile split, but K=hidden always divides, with GSPMD psum-ing the
# partial logits. Group-wise scales (`scale_g` [K/g, N]) follow their
# kernel: column-parallel sites shard N, row-parallel sites shard the
# K-group rows.
LLAMA_INT4_PARTITION_RULES = (
    # OVERRIDES (first match wins) of the inherited int8 lm_head rules:
    # the int4 lm_head is K-sharded, so its per-channel [vocab] scale is
    # replicated (the int8 rule would shard it against unsharded partial
    # logits, inserting a gather every decode step)
    PartitionRule(r"lm_head/scale$", ()),
    PartitionRule(r"lm_head/scale_g$", ("tensor", None)),
    PartitionRule(r"lm_head/kernel_p$", ("tensor", None)),
    PartitionRule(r"attn/(q|k|v)/scale_g$", (None, "tensor")),
    PartitionRule(r"attn/o/scale_g$", ("tensor", None)),
    PartitionRule(r"mlp/(gate|up)/scale_g$", (None, "tensor")),
    PartitionRule(r"mlp/down/scale_g$", ("tensor", None)),
) + LLAMA_QUANT_PARTITION_RULES + (
    PartitionRule(r"attn/(q|k|v)/kernel_p$", (None, "tensor")),
    PartitionRule(r"attn/o/kernel_p$", ("tensor", None)),
    PartitionRule(r"mlp/(gate|up)/kernel_p$", (None, "tensor")),
    PartitionRule(r"mlp/down/kernel_p$", ("tensor", None)),
)


def assert_int4_tp_compatible(config: "LlamaConfig", tensor: int) -> None:
    """Refuse tensor-parallel degrees whose per-device channel ranges
    split an int4 packing tile — a misaligned shard pairs nibbles with
    the wrong output channels and decodes GARBAGE with no exception.
    Call before sharding a ``weight_bits=4`` tree.

    With ``config.int4_tp`` set (the degree ``quantize_params(tensor=…)``
    packed for), any ``tensor`` DIVIDING it is slab-aligned — 8B packs
    for tp=8 with tiles q 512 / k,v 128 / gate,up 256. A tree packed at
    the default ``int4_tp=1`` keeps the old single-chip rule (8B then
    passes tp=2; k/v break at tp=4 — 1024/4 = 256 per device vs tile
    512). The lm_head is exempt: it shards K, which any degree divides.
    """
    from unionml_tpu.ops.int4_matmul import tile_for

    if tensor <= 1 or config.weight_bits != 4:
        return
    # column-parallel sites only (o/down/lm_head shard K — row sharding
    # leaves output channels whole)
    sites = (
        ("attn/q", config.num_heads * config.head_dim, config.hidden_dim),
        ("attn/k", config.num_kv_heads * config.head_dim, config.hidden_dim),
        ("mlp/gate", config.mlp_dim, config.hidden_dim),
    )
    for name, n, k in sites:
        tile = tile_for(n, k, shards=config.int4_tp)
        if tile and (n // tensor) % tile:
            raise ValueError(
                f"int4 layer {name}: {n} channels / tensor={tensor} = "
                f"{n // tensor} per device, not a multiple of the packing "
                f"tile {tile} (tree packed for int4_tp={config.int4_tp}) — "
                "the shard would unpack wrong channels. Re-quantize with "
                f"quantize_params(tensor={tensor}) and "
                f"LlamaConfig(int4_tp={tensor}), serve at a divisor of "
                f"{config.int4_tp}, or serve this model int8."
            )

# MoE configs (num_experts > 0): expert weights [E, d, h] shard E over the
# `expert` mesh axis (GSPMD turns the one-hot dispatch einsums into
# all_to_all on that axis) and the hidden dim over `tensor`; the router is
# replicated — it is tiny and every device routes its own tokens.
LLAMA_MOE_PARTITION_RULES = (
    PartitionRule(r"moe/w_(gate|up)$", ("expert", None, "tensor")),
    PartitionRule(r"moe/w_down$", ("expert", "tensor", None)),
    # int8 serving form: [E, K, N] weights + [E, N] scales
    PartitionRule(r"moe/w_(gate|up)_q$", ("expert", None, "tensor")),
    PartitionRule(r"moe/w_(gate|up)_scale$", ("expert", "tensor")),
    PartitionRule(r"moe/w_down_q$", ("expert", "tensor", None)),
    PartitionRule(r"moe/w_down_scale$", ("expert", None)),
    PartitionRule(r"moe/router_kernel$", (None,)),
    # includes the attention/mlp/lm_head int8 rules (supersets the fp set),
    # so one rule list covers fp and quantized MoE models alike
) + LLAMA_QUANT_PARTITION_RULES
