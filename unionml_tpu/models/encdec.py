"""Encoder-decoder transformer — the seq2seq family of the model zoo.

No reference counterpart (the reference delegates modeling to user
code); this rounds the zoo out beyond encoders (BERT) and decoders
(Llama) so translation/summarization-style apps get the same
three-line-step treatment (SURVEY.md §2.4 model-zoo addition).

TPU-first choices, consistent with the rest of the zoo:

- pre-LN blocks, bf16 compute with fp32 master weights and fp32
  normalization statistics;
- self-attention carries RoPE (no learned position tables to shard or
  bound); cross-attention is position-free and always fully visible,
  masked only by the source padding mask;
- the decoder threads the same functional KV cache as Llama for its
  SELF-attention, so generation is one jitted prefill-free scan. Cross
  k/v are recomputed from the (loop-invariant) encoder output inside
  the scan body — XLA hoists them out of the loop, which is why there
  is no cross-KV cache to plumb;
- Megatron partition rules: q/k/v/up shard output features over
  ``tensor``, o/down shard input features — two collectives per block.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from unionml_tpu.models.layers import Attention, MlpBlock, RMSNorm
from unionml_tpu.parallel.sharding import PartitionRule

Cache = Tuple[Tuple[jnp.ndarray, jnp.ndarray], ...]


@dataclass(frozen=True)
class EncDecConfig:
    vocab_size: int = 32_128
    hidden_dim: int = 768
    num_encoder_layers: int = 12
    num_decoder_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 2048
    rope_theta: float = 10_000.0
    max_len: int = 512
    dtype: str = "bfloat16"

    @staticmethod
    def tiny(vocab_size: int = 512, **overrides) -> "EncDecConfig":
        kwargs = dict(
            vocab_size=vocab_size, hidden_dim=64, num_encoder_layers=2,
            num_decoder_layers=2, num_heads=4, mlp_dim=128, max_len=64,
        )
        kwargs.update(overrides)
        return EncDecConfig(**kwargs)

    @property
    def head_dim(self) -> int:
        return self.hidden_dim // self.num_heads


class _EncoderBlock(nn.Module):
    config: EncDecConfig

    @nn.compact
    def __call__(self, x, src_mask):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        h = RMSNorm(dtype=dtype, name="attn_norm")(x)
        # bidirectional self-attention; padded source tokens are hidden
        # through the cross-attention kv path (kv=h with a source mask)
        x = x + Attention(
            num_heads=cfg.num_heads, head_dim=cfg.head_dim, rope=False,
            causal=False, dtype=dtype, name="attn",
        )(h, kv=h, kv_mask=src_mask)
        h = RMSNorm(dtype=dtype, name="mlp_norm")(x)
        return x + MlpBlock(hidden_dim=cfg.mlp_dim, gated=True, dtype=dtype, name="mlp")(h)


class _DecoderBlock(nn.Module):
    config: EncDecConfig

    @nn.compact
    def __call__(self, x, enc_out, src_mask, *, positions=None, cache=None,
                 cache_index=None):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        h = RMSNorm(dtype=dtype, name="self_norm")(x)
        self_attn = Attention(
            num_heads=cfg.num_heads, head_dim=cfg.head_dim, rope=True,
            rope_theta=cfg.rope_theta, causal=True, dtype=dtype, name="self_attn",
        )
        if cache is not None:
            a, new_cache = self_attn(
                h, positions=positions, cache=cache, cache_index=cache_index
            )
        else:
            a, new_cache = self_attn(h, positions=positions), None
        x = x + a
        h = RMSNorm(dtype=dtype, name="cross_norm")(x)
        x = x + Attention(
            num_heads=cfg.num_heads, head_dim=cfg.head_dim, rope=False,
            causal=False, dtype=dtype, name="cross_attn",
        )(h, kv=enc_out, kv_mask=src_mask)
        h = RMSNorm(dtype=dtype, name="mlp_norm")(x)
        x = x + MlpBlock(hidden_dim=cfg.mlp_dim, gated=True, dtype=dtype, name="mlp")(h)
        return x, new_cache


class EncoderDecoder(nn.Module):
    """Seq2seq transformer with a shared source/target embedding.

    Call forms:

    - training: ``module.apply(vars, src_ids, tgt_ids, src_mask=...)``
      → decoder logits [B, S_tgt, V] (teacher forcing — shift outside);
    - encode once: ``module.apply(vars, src_ids, src_mask=...,
      method=EncoderDecoder.encode)`` → enc_out;
    - cached decode step: ``module.apply(vars, tgt_tok, enc_out,
      src_mask, cache, cache_index, method=EncoderDecoder.decode)``
      → (logits, new_cache) — the generation scan body.
    """

    config: EncDecConfig = field(default_factory=EncDecConfig)

    def setup(self):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        self.embed = nn.Embed(cfg.vocab_size, cfg.hidden_dim, dtype=dtype, name="embed")
        self.enc_blocks = [
            _EncoderBlock(cfg, name=f"enc_{i}")
            for i in range(cfg.num_encoder_layers)
        ]
        self.enc_norm = RMSNorm(dtype=dtype, name="enc_norm")
        self.dec_blocks = [
            _DecoderBlock(cfg, name=f"dec_{i}")
            for i in range(cfg.num_decoder_layers)
        ]
        self.dec_norm = RMSNorm(dtype=dtype, name="dec_norm")
        self.lm_head = nn.Dense(cfg.vocab_size, dtype=jnp.float32, name="lm_head")

    def encode(self, src_ids, *, src_mask=None):
        if src_mask is None:
            src_mask = jnp.ones(src_ids.shape, bool)
        x = self.embed(src_ids)
        for block in self.enc_blocks:
            x = block(x, src_mask)
        return self.enc_norm(x)

    def decode(self, tgt_ids, enc_out, src_mask=None, cache=None, cache_index=None):
        if src_mask is None:
            src_mask = jnp.ones(enc_out.shape[:2], bool)
        x = self.embed(tgt_ids)
        new_cache = []
        for i, block in enumerate(self.dec_blocks):
            layer_cache = cache[i] if cache is not None else None
            x, c = block(
                x, enc_out, src_mask,
                cache=layer_cache, cache_index=cache_index,
            )
            new_cache.append(c)
        x = self.dec_norm(x)
        logits = self.lm_head(x.astype(jnp.float32))
        if cache is not None:
            return logits, tuple(new_cache)
        return logits

    def __call__(self, src_ids, tgt_ids, *, src_mask=None):
        enc_out = self.encode(src_ids, src_mask=src_mask)
        return self.decode(tgt_ids, enc_out, src_mask)


def init_decoder_cache(
    config: EncDecConfig, batch: int, max_len: Optional[int] = None,
    dtype=jnp.bfloat16,
) -> Cache:
    """Zero-filled decoder SELF-attention cache (cross needs none)."""
    max_len = max_len or config.max_len
    shape = (batch, max_len, config.num_heads, config.head_dim)
    zeros = jnp.zeros(shape, dtype)
    return tuple((zeros, zeros) for _ in range(config.num_decoder_layers))


def make_seq2seq_generator(
    module: EncoderDecoder,
    *,
    max_new_tokens: int,
    bos_id: int = 1,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> "callable":
    """Build ``generate(params, src_ids, key=None, src_mask=None) ->
    tokens [B, max_new_tokens]``: encode once, then one ``lax.scan``
    decode with the self-attention KV cache (same static-shape design
    as the Llama generator — one executable per (batch, src_len))."""
    from unionml_tpu.models.generate import make_sampler

    cfg = module.config
    sample = make_sampler(temperature=temperature, top_k=top_k, top_p=top_p)
    total = max_new_tokens + 1  # bos occupies slot 0

    def generate(params, src_ids, key=None, src_mask=None):
        batch = src_ids.shape[0]
        if key is None:
            if temperature != 0.0:
                raise ValueError(
                    "temperature sampling needs an explicit PRNG key: "
                    "generate(params, src_ids, key)"
                )
            key = jax.random.PRNGKey(0)
        enc_out = module.apply(
            {"params": params}, src_ids, src_mask=src_mask,
            method=EncoderDecoder.encode,
        )
        # cache in the module's compute dtype: a bf16 cache under an fp32
        # config would break cached-vs-uncached decode parity
        cache = init_decoder_cache(cfg, batch, total, dtype=jnp.dtype(cfg.dtype))
        bos = jnp.full((batch, 1), bos_id, jnp.int32)

        def step(carry, key_step):
            cache, tok, index, done = carry
            logits, cache = module.apply(
                {"params": params}, tok, enc_out, src_mask, cache, index,
                method=EncoderDecoder.decode,
            )
            nxt = sample(logits[:, -1], key_step)
            if eos_id is not None:
                nxt = jnp.where(done, pad_id, nxt)
                done = done | (nxt == eos_id)
            return (cache, nxt[:, None], index + 1, done), nxt

        keys = jax.random.split(key, max_new_tokens)
        done0 = jnp.zeros(batch, bool)
        (_, _, _, _), toks = jax.lax.scan(
            step, (cache, bos, jnp.int32(0), done0), keys
        )
        return toks.T  # [B, max_new_tokens]

    return jax.jit(generate)


def seq2seq_step(
    module: EncoderDecoder,
    *,
    ignore_id: int = -100,
    pad_id: int = 0,
    accumulate_steps: int = 1,
):
    """Teacher-forced seq2seq training step.

    ``batch = (src_ids, tgt_ids)``: the decoder consumes
    ``tgt_ids[:, :-1]`` and is supervised on ``tgt_ids[:, 1:]`` with
    ``ignore_id`` positions (padding) masked out of the mean CE —
    the ``(state, batch) -> (state, metrics)`` step-trainer contract.
    Source padding: ``src_ids == pad_id`` is hidden from every attention
    over the source (set ``pad_id`` to your tokenizer's — id 0 is only
    the default, not an assumption).

    ``accumulate_steps > 1``: gradient accumulation over a leading
    microbatch axis, like the other zoo step factories.
    """
    from unionml_tpu.models.train import (
        _bind_frozen,
        accumulated_value_and_grad,
        masked_cross_entropy,
    )

    def loss_fn(params, microbatch):
        src_ids, tgt_ids = microbatch
        inputs, targets = tgt_ids[:, :-1], tgt_ids[:, 1:]
        logits = module.apply(
            {"params": params}, src_ids, inputs, src_mask=src_ids != pad_id
        )
        loss = masked_cross_entropy(logits, targets, ignore_id=ignore_id)
        return loss, {"z": jnp.float32(0.0)}

    def step(state, batch):
        bound = _bind_frozen(loss_fn, state)
        if accumulate_steps > 1:
            (loss, _), grads = accumulated_value_and_grad(
                bound, state.params, batch
            )
        else:
            (loss, _), grads = jax.value_and_grad(bound, has_aux=True)(
                state.params, batch
            )
        state = state.apply_gradients(grads=grads)
        return state, {"loss": loss, "perplexity": jnp.exp(loss)}

    return step


def make_seq2seq_predictor(
    module: EncoderDecoder,
    *,
    max_new_tokens: int = 32,
    src_buckets: tuple = (16, 32, 64, 128),
    bos_id: int = 1,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
    seed: int = 0,
    **gen_kwargs,
) -> "callable":
    """An ``@model.predictor``-compatible fn over token-id sources.

    The seq2seq counterpart of ``make_lm_predictor``: accepts a list of
    (possibly ragged) source token-id lists, right-pads each to the
    smallest covering source bucket and the batch to the next power of
    two, generates through :func:`make_seq2seq_generator`, and returns
    one token list per source — trimmed at ``eos_id`` when set. A
    source longer than the largest bucket raises (head-truncating a
    seq2seq source would silently drop the tail the decoder needs —
    configure ``src_buckets`` for your traffic instead). Padded
    source positions are masked out of every attention, so a padded
    source generates exactly what its unpadded form would (tested).
    XLA compiles ``len(src_buckets) * (log2(max_batch) + 1)``
    executables (batch sizes 1, 2, ..., max_batch).

    ``.warmup(state, max_batch=..., buckets=...)`` pre-compiles every
    (bucket, power-of-two batch) executable — same contract (and same
    strict bucket validation) as ``make_lm_predictor``'s warmup.
    """
    import numpy as np

    buckets = tuple(sorted(set(int(b) for b in src_buckets)))
    gen = make_seq2seq_generator(
        module, max_new_tokens=max_new_tokens, bos_id=bos_id,
        eos_id=eos_id, pad_id=pad_id, **gen_kwargs,
    )
    from unionml_tpu.models.train import resolve_params

    key_state = {"key": jax.random.PRNGKey(seed)}
    temperature = gen_kwargs.get("temperature", 0.0)

    def predictor(state, sources) -> list:
        params = resolve_params(state)
        rows = [np.asarray(s, dtype=np.int32).ravel() for s in sources]
        longest = max(len(r) for r in rows)
        bucket = next((b for b in buckets if b >= longest), buckets[-1])
        n = len(rows)
        n_padded = 1 << (n - 1).bit_length()
        batch = np.full((n_padded, bucket), pad_id, np.int32)
        mask = np.zeros((n_padded, bucket), bool)
        if longest > buckets[-1]:
            raise ValueError(
                f"source length {longest} exceeds the largest configured "
                f"bucket {buckets[-1]}; add a larger bucket to src_buckets"
            )
        for i in range(n_padded):
            r = rows[min(i, n - 1)]
            batch[i, : len(r)] = r
            mask[i, : len(r)] = True
        key_state["key"], sub = jax.random.split(key_state["key"])
        key = sub if temperature != 0.0 else None
        out = np.asarray(gen(params, jnp.asarray(batch), key, jnp.asarray(mask)))
        results = []
        for row in out[:n]:
            toks = row.tolist()
            if eos_id is not None and eos_id in toks:
                toks = toks[: toks.index(eos_id) + 1]
            results.append(toks)
        return results

    def warmup(state, *, max_batch: int = 8, buckets: Optional[tuple] = None,
               _all=buckets) -> int:
        if buckets is not None and not buckets:
            # an empty tuple would silently warm nothing — same guard as
            # the LM predictor's warmup
            raise ValueError(
                "warmup got an empty bucket tuple — pass buckets=None to "
                "warm every configured bucket"
            )
        use = _all if buckets is None else tuple(buckets)
        unknown = sorted(set(use) - set(_all))
        if unknown:
            raise ValueError(
                f"warmup buckets {unknown} are not configured ({_all})"
            )
        compiled = 0
        top = 1 << (max(1, max_batch) - 1).bit_length()
        for b in use:
            size = 1
            while size <= top:
                predictor(state, np.ones((size, b), np.int32))
                compiled += 1
                size *= 2
        return compiled

    predictor.warmup = warmup
    return predictor


# Megatron-style TP over the `tensor` axis: two collectives per block
# (one after each attention's o, one after each MLP down); the shared
# embedding and the head shard vocab.
ENCDEC_PARTITION_RULES = (
    PartitionRule(r"(self_attn|cross_attn|attn)/(q|k|v)/kernel$", (None, "tensor", None)),
    PartitionRule(r"(self_attn|cross_attn|attn)/o/kernel$", ("tensor", None, None)),
    PartitionRule(r"mlp/(gate|up)/kernel$", (None, "tensor")),
    PartitionRule(r"mlp/down/kernel$", ("tensor", None)),
    PartitionRule(r"embed/embedding$", ("tensor", None)),
    PartitionRule(r"lm_head/kernel$", (None, "tensor")),
)
