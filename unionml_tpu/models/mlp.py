"""MLP classifier — the MNIST single-chip config (BASELINE.json config #2).

Replaces the reference's "bring your own sklearn/torch model" for the
minimum end-to-end slice (SURVEY.md §7): a flax module whose train step is
one fused jit program on a single chip.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn


@dataclass(frozen=True)
class MlpConfig:
    num_classes: int = 10
    hidden_dims: Sequence[int] = (256, 256)
    dropout: float = 0.0
    dtype: str = "bfloat16"


class Mlp(nn.Module):
    config: MlpConfig = field(default_factory=MlpConfig)

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        x = x.reshape((x.shape[0], -1)).astype(dtype)
        for i, dim in enumerate(cfg.hidden_dims):
            x = nn.Dense(dim, dtype=dtype, name=f"dense_{i}")(x)
            x = nn.relu(x)
            if cfg.dropout and train:
                x = nn.Dropout(cfg.dropout, deterministic=not train)(x)
        return nn.Dense(cfg.num_classes, dtype=jnp.float32, name="head")(x)


MLP_PARTITION_RULES = ()  # small enough to replicate; FSDP fallback applies
