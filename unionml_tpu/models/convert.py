"""Pretrained-checkpoint ingestion: HF safetensors → framework params.

TPU-native replacement for the reference's model-artifact loaders
(reference: unionml/model.py:965-988 reconstructs real sklearn/torch
objects from saved artifacts; remote.py:186-194 fetches them from the
registry). For the LLM flagship the pretrained artifact of record is a
HuggingFace safetensors checkpoint, and an 8B fp32 tree (~32 GB) cannot
be materialized whole — on host *or* chip. So the converter STREAMS:

- each checkpoint tensor is read one at a time via ``safetensors``
  zero-copy slicing (multi-shard ``model.safetensors.index.json``
  layouts supported), mapped through a per-model name/layout spec, and
  uploaded before the next is touched — peak host memory stays ~one
  tensor (asserted by ``tests/unit/test_convert.py`` with tracemalloc);
- with ``quantize=True`` each eligible matmul kernel is quantized to
  int8 per output channel ON DEVICE with the same
  :func:`~unionml_tpu.models.quantization._quantize_kernel_2d` recipe
  that :func:`~unionml_tpu.models.quantization.quantize_params` applies
  to in-memory trees, so a streamed-int8 load is bit-identical to
  load-fp-then-quantize — without ever holding the fp tree;
- the layout specs are invertible: :func:`export_llama_safetensors` /
  :func:`export_bert_safetensors` write framework params back out as an
  HF-layout checkpoint (also the test fixture generator).

Conventions verified by test (``tests/unit/test_convert_hf_parity.py``
compares logits against ``transformers``' torch reference models built
from the same checkpoint): this zoo's rotary embedding is the HF
rotate-half convention (``models/layers.py:rotary_embedding`` splits the
head dim in half — exactly ``transformers``' ``rotate_half``), so HF
Llama q/k weights map with a pure transpose+reshape, no permutation.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from unionml_tpu.models.bert import BertConfig
from unionml_tpu.models.llama import LlamaConfig

__all__ = [
    "TensorSpec",
    "GroupSpec",
    "llama_tensor_specs",
    "bert_tensor_specs",
    "vit_tensor_specs",
    "llama_config_from_hf",
    "bert_config_from_hf",
    "vit_config_from_hf",
    "load_llama_checkpoint",
    "load_bert_checkpoint",
    "load_vit_checkpoint",
    "export_llama_safetensors",
    "export_bert_safetensors",
    "export_vit_safetensors",
    "merge_pretrained",
]


@dataclass(frozen=True)
class TensorSpec:
    """One checkpoint tensor ↔ one framework param.

    ``to_framework`` / ``to_hf`` are inverse numpy layout transforms
    (transpose/reshape only — dtype is handled by the loader).
    ``quantizable`` marks matmul kernels eligible for the streamed-int8
    path; ``fallback`` names an alternate HF tensor (tied-embedding
    checkpoints omit ``lm_head.weight``).
    """

    path: Tuple[str, ...]
    hf_name: str
    to_framework: Callable[[np.ndarray], np.ndarray]
    to_hf: Callable[[np.ndarray], np.ndarray]
    quantizable: bool = False
    fallback: Optional[str] = None
    # absent-from-checkpoint tolerated (e.g. the pooler in bare-encoder
    # BERT checkpoints) — the loader skips instead of raising
    optional: bool = False
    # never cast to the serving dtype (fp32-by-contract leaves: the MoE
    # router master weights)
    keep_dtype: bool = False


@dataclass(frozen=True)
class GroupSpec:
    """N checkpoint tensors ↔ one stacked framework param (MoE experts:
    HF Mixtral stores per-expert ``w1/w2/w3`` matrices; the zoo stacks
    them as ``[E, K, N]`` so expert parallelism can shard the leading
    axis). The transforms are PER ELEMENT (stacking/unstacking along the
    leading axis is the loader's job) so the streaming contract holds:
    one expert tensor is resident at a time, written into a
    preallocated stack — never ``E`` tensors plus a stacked copy.
    ``quantizable`` groups stream through the per-(expert, out-channel)
    int8 recipe one expert at a time (bit-identical to
    ``quantize_params``'s vmapped form — vmap of the same 2D kernel)."""

    path: Tuple[str, ...]
    hf_names: Tuple[str, ...]
    to_framework: Callable[[np.ndarray], np.ndarray]
    to_hf: Callable[[np.ndarray], np.ndarray]
    quantizable: bool = False


def _ident(w: np.ndarray) -> np.ndarray:
    return w


def _t(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(w.T)


def _split_heads(heads: int, head_dim: int):
    """HF ``[heads*hd, D]`` proj weight → framework ``[D, heads, hd]``."""

    def fwd(w: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(w.T).reshape(w.shape[1], heads, head_dim)

    def inv(w: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(w.reshape(w.shape[0], heads * head_dim).T)

    return fwd, inv


def _merge_heads(heads: int, head_dim: int):
    """HF ``[D, heads*hd]`` out-proj weight → framework ``[heads, hd, D]``."""

    def fwd(w: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(w.T).reshape(heads, head_dim, w.shape[0])

    def inv(w: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(w.reshape(heads * head_dim, w.shape[-1]).T)

    return fwd, inv


def _head_bias(heads: int, head_dim: int):
    """HF ``[heads*hd]`` qkv bias → framework ``[heads, hd]``."""

    def fwd(w: np.ndarray) -> np.ndarray:
        return w.reshape(heads, head_dim)

    def inv(w: np.ndarray) -> np.ndarray:
        return w.reshape(heads * head_dim)

    return fwd, inv


# ---------------------------------------------------------------------------
# Llama


def llama_tensor_specs(config: LlamaConfig) -> List[Any]:
    """The HF-Llama/Mixtral ↔ :class:`~unionml_tpu.models.llama.Llama`
    tensor map.

    Dense family: embed, per-block attention q/k/v/o + norms + SwiGLU
    MLP, final norm, LM head (falling back to the tied
    ``model.embed_tokens.weight`` when ``lm_head.weight`` is absent, as
    in Llama-3.2-1B/3B checkpoints). With ``config.num_experts`` the MLP
    entries become the Mixtral block-sparse layout: fp32 router + three
    per-expert :class:`GroupSpec` stacks.
    """
    hd = config.head_dim
    qf, qi = _split_heads(config.num_heads, hd)
    kf, ki = _split_heads(config.num_kv_heads, hd)
    of, oi = _merge_heads(config.num_heads, hd)

    specs: List[Any] = [
        TensorSpec(
            ("embed", "embedding"), "model.embed_tokens.weight", _ident, _ident
        ),
    ]
    for i in range(config.num_layers):
        b = f"block_{i}"
        L = f"model.layers.{i}"
        specs += [
            TensorSpec((b, "attn", "q", "kernel"), f"{L}.self_attn.q_proj.weight", qf, qi, True),
            TensorSpec((b, "attn", "k", "kernel"), f"{L}.self_attn.k_proj.weight", kf, ki, True),
            TensorSpec((b, "attn", "v", "kernel"), f"{L}.self_attn.v_proj.weight", kf, ki, True),
            TensorSpec((b, "attn", "o", "kernel"), f"{L}.self_attn.o_proj.weight", of, oi, True),
            TensorSpec((b, "attn_norm", "scale"), f"{L}.input_layernorm.weight", _ident, _ident),
            TensorSpec((b, "mlp_norm", "scale"), f"{L}.post_attention_layernorm.weight", _ident, _ident),
        ]
        if config.num_experts:
            # Mixtral block-sparse MoE: per-expert w1 (gate) / w3 (up) /
            # w2 (down) stack into the zoo's [E, K, N] layout (expert
            # parallelism shards the leading axis); the router stays
            # fp32 BY CONTRACT (tiny routing updates round to zero in
            # bf16 — ops/moe.py), hence keep_dtype. Routing semantics
            # match: both renormalize the top-k softmax weights.
            M = f"{L}.block_sparse_moe"
            experts = range(config.num_experts)
            specs += [
                TensorSpec(
                    (b, "moe", "router_kernel"), f"{M}.gate.weight",
                    _t, _t, keep_dtype=True,
                ),
                GroupSpec(
                    (b, "moe", "w_gate"),
                    tuple(f"{M}.experts.{e}.w1.weight" for e in experts),
                    _t, _t, True,
                ),
                GroupSpec(
                    (b, "moe", "w_up"),
                    tuple(f"{M}.experts.{e}.w3.weight" for e in experts),
                    _t, _t, True,
                ),
                GroupSpec(
                    (b, "moe", "w_down"),
                    tuple(f"{M}.experts.{e}.w2.weight" for e in experts),
                    _t, _t, True,
                ),
            ]
        else:
            specs += [
                TensorSpec((b, "mlp", "gate", "kernel"), f"{L}.mlp.gate_proj.weight", _t, _t, True),
                TensorSpec((b, "mlp", "up", "kernel"), f"{L}.mlp.up_proj.weight", _t, _t, True),
                TensorSpec((b, "mlp", "down", "kernel"), f"{L}.mlp.down_proj.weight", _t, _t, True),
            ]
    specs.append(
        TensorSpec(
            ("final_norm", "scale"), "model.norm.weight", _ident, _ident
        )
    )
    specs.append(
        TensorSpec(
            ("lm_head", "kernel"), "lm_head.weight", _t, _t, True,
            fallback="model.embed_tokens.weight",
        )
    )
    return specs


def llama_config_from_hf(config_json: Dict[str, Any], **overrides: Any) -> LlamaConfig:
    """Build a :class:`LlamaConfig` from an HF ``config.json`` dict.

    ``overrides`` pass through to the dataclass (e.g. ``quantized=True``,
    ``max_len=8192`` to cap the KV-cache geometry below the checkpoint's
    ``max_position_embeddings``).
    """
    kwargs: Dict[str, Any] = dict(
        vocab_size=config_json["vocab_size"],
        hidden_dim=config_json["hidden_size"],
        num_layers=config_json["num_hidden_layers"],
        num_heads=config_json["num_attention_heads"],
        num_kv_heads=config_json.get(
            "num_key_value_heads", config_json["num_attention_heads"]
        ),
        mlp_dim=config_json["intermediate_size"],
        rope_theta=float(config_json.get("rope_theta", 10_000.0)),
        norm_eps=float(config_json.get("rms_norm_eps", 1e-5)),
        max_len=config_json.get("max_position_embeddings", 8192),
    )
    if config_json.get("num_local_experts"):
        # Mixtral block-sparse MoE (routing semantics match: both this
        # zoo and HF renormalize the top-k softmax weights)
        kwargs["num_experts"] = config_json["num_local_experts"]
        kwargs["num_selected"] = config_json.get("num_experts_per_tok", 2)
    scaling = config_json.get("rope_scaling")
    if scaling:
        # Llama-3.1/3.2 long-context checkpoints; silently dropping this
        # would compute unscaled frequencies — wrong logits, no signal
        rope_type = scaling.get("rope_type", scaling.get("type"))
        if rope_type != "llama3":
            raise NotImplementedError(
                f"rope_scaling type {rope_type!r} is not supported "
                "(llama3-type rescaling only)"
            )
        kwargs["rope_scaling"] = (
            float(scaling["factor"]),
            float(scaling["low_freq_factor"]),
            float(scaling["high_freq_factor"]),
            int(scaling["original_max_position_embeddings"]),
        )
    kwargs.update(overrides)
    return LlamaConfig(**kwargs)


# ---------------------------------------------------------------------------
# BERT


def bert_tensor_specs(
    config: BertConfig, *, encoder_key: str = "encoder"
) -> List[TensorSpec]:
    """The HF-BERT ↔ :class:`~unionml_tpu.models.bert.BertClassifier` map.

    Framework paths are rooted under ``encoder_key`` (the
    ``BertClassifier``/``BertMlm`` submodule name; pass ``""`` for a bare
    :class:`BertEncoder` tree). Covers embeddings (word/position/type +
    LayerNorm), every post-LN block, and the pooler; task heads are the
    fine-tune target and stay at their fresh initialization (merge with
    :func:`merge_pretrained`). HF checkpoints may or may not carry a
    ``bert.`` name prefix — the loader detects it.
    """
    hd = config.hidden_dim // config.num_heads
    qf, qi = _split_heads(config.num_heads, hd)
    of, oi = _merge_heads(config.num_heads, hd)
    bf, bi = _head_bias(config.num_heads, hd)
    root: Tuple[str, ...] = (encoder_key,) if encoder_key else ()
    enc = lambda *p: root + p  # noqa: E731
    specs: List[TensorSpec] = [
        TensorSpec(enc("tok_embed", "embedding"), "embeddings.word_embeddings.weight", _ident, _ident),
        TensorSpec(enc("pos_embed", "embedding"), "embeddings.position_embeddings.weight", _ident, _ident),
        TensorSpec(enc("type_embed", "embedding"), "embeddings.token_type_embeddings.weight", _ident, _ident),
        TensorSpec(enc("ln_embed", "scale"), "embeddings.LayerNorm.weight", _ident, _ident),
        TensorSpec(enc("ln_embed", "bias"), "embeddings.LayerNorm.bias", _ident, _ident),
    ]
    hf_names = {"q": "query", "k": "key", "v": "value"}
    for i in range(config.num_layers):
        b = f"block_{i}"
        L = f"encoder.layer.{i}"
        for ours, theirs in hf_names.items():
            specs += [
                # quantizable stays False on every BERT spec: the
                # streamed-int8 geometry dispatch (`path[-2] == "o"`)
                # knows the Llama zoo's layouts only, and attn_o's
                # [heads, hd, D] kernel would mis-fold silently
                TensorSpec(
                    enc(b, f"attn_{ours}", "kernel"),
                    f"{L}.attention.self.{theirs}.weight", qf, qi,
                ),
                TensorSpec(enc(b, f"attn_{ours}", "bias"), f"{L}.attention.self.{theirs}.bias", bf, bi),
            ]
        specs += [
            TensorSpec(enc(b, "attn_o", "kernel"), f"{L}.attention.output.dense.weight", of, oi),
            TensorSpec(enc(b, "attn_o", "bias"), f"{L}.attention.output.dense.bias", _ident, _ident),
            TensorSpec(enc(b, "ln1", "scale"), f"{L}.attention.output.LayerNorm.weight", _ident, _ident),
            TensorSpec(enc(b, "ln1", "bias"), f"{L}.attention.output.LayerNorm.bias", _ident, _ident),
            TensorSpec(enc(b, "mlp", "up", "kernel"), f"{L}.intermediate.dense.weight", _t, _t),
            TensorSpec(enc(b, "mlp", "up", "bias"), f"{L}.intermediate.dense.bias", _ident, _ident),
            TensorSpec(enc(b, "mlp", "down", "kernel"), f"{L}.output.dense.weight", _t, _t),
            TensorSpec(enc(b, "mlp", "down", "bias"), f"{L}.output.dense.bias", _ident, _ident),
            TensorSpec(enc(b, "ln2", "scale"), f"{L}.output.LayerNorm.weight", _ident, _ident),
            TensorSpec(enc(b, "ln2", "bias"), f"{L}.output.LayerNorm.bias", _ident, _ident),
        ]
    specs += [
        TensorSpec(("pooler", "kernel"), "pooler.dense.weight", _t, _t, optional=True),
        TensorSpec(("pooler", "bias"), "pooler.dense.bias", _ident, _ident, optional=True),
    ]
    return specs


def bert_config_from_hf(config_json: Dict[str, Any], **overrides: Any) -> BertConfig:
    """Build a :class:`BertConfig` from an HF ``config.json`` dict."""
    act = config_json.get("hidden_act", "gelu")
    if act not in ("gelu", "gelu_new", "gelu_pytorch_tanh"):
        raise NotImplementedError(
            f"hidden_act {act!r} is not supported (gelu variants only)"
        )
    kwargs: Dict[str, Any] = dict(
        vocab_size=config_json["vocab_size"],
        max_len=config_json.get("max_position_embeddings", 512),
        num_types=config_json.get("type_vocab_size", 2),
        hidden_dim=config_json["hidden_size"],
        num_layers=config_json["num_hidden_layers"],
        num_heads=config_json["num_attention_heads"],
        mlp_dim=config_json["intermediate_size"],
        # "gelu" is the erf form BERT was pretrained with; the framework
        # default is the tanh approximation, so checkpoint-derived
        # configs must opt in to the exact op for faithful inference
        gelu_exact=(act == "gelu"),
    )
    kwargs.update(overrides)
    return BertConfig(**kwargs)


# ---------------------------------------------------------------------------
# ViT


def vit_tensor_specs(config: "ViTConfig") -> List[TensorSpec]:
    """The HF-ViT ↔ :class:`~unionml_tpu.models.vit.ViT` tensor map.

    Pre-LN blocks map one-to-one (``layernorm_before``→``ln1``,
    ``layernorm_after``→``ln2``); the patch conv transposes torch OIHW →
    flax HWIO; q/k/v/o carry biases (``ViTConfig.qkv_bias=True``). The
    classification ``head`` maps from ``classifier.*`` when present
    (ViTForImageClassification) and is otherwise the fine-tune target.
    """
    hd = config.hidden_dim // config.num_heads
    qf, qi = _split_heads(config.num_heads, hd)
    of, oi = _merge_heads(config.num_heads, hd)
    bf, bi = _head_bias(config.num_heads, hd)

    def conv_fwd(w: np.ndarray) -> np.ndarray:   # OIHW → HWIO
        return np.ascontiguousarray(w.transpose(2, 3, 1, 0))

    def conv_inv(w: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(w.transpose(3, 2, 0, 1))

    specs: List[TensorSpec] = [
        TensorSpec(("cls",), "embeddings.cls_token", _ident, _ident),
        TensorSpec(("pos_embed",), "embeddings.position_embeddings", _ident, _ident),
        TensorSpec(
            ("patch_embed", "kernel"),
            "embeddings.patch_embeddings.projection.weight", conv_fwd, conv_inv,
        ),
        TensorSpec(
            ("patch_embed", "bias"),
            "embeddings.patch_embeddings.projection.bias", _ident, _ident,
        ),
    ]
    hf_names = {"q": "query", "k": "key", "v": "value"}
    for i in range(config.num_layers):
        b = f"block_{i}"
        L = f"encoder.layer.{i}"
        for ours, theirs in hf_names.items():
            specs.append(TensorSpec(
                (b, "attn", ours, "kernel"),
                f"{L}.attention.attention.{theirs}.weight", qf, qi,
            ))
            if config.qkv_bias:
                # bias-free configs (the zoo's trained-from-scratch
                # default) have no bias params to fill — emitting the
                # specs anyway would reject bias-free checkpoints
                specs.append(TensorSpec(
                    (b, "attn", ours, "bias"),
                    f"{L}.attention.attention.{theirs}.bias", bf, bi,
                ))
        specs.append(TensorSpec(
            (b, "attn", "o", "kernel"),
            f"{L}.attention.output.dense.weight", of, oi,
        ))
        if config.qkv_bias:
            specs.append(TensorSpec(
                (b, "attn", "o", "bias"),
                f"{L}.attention.output.dense.bias", _ident, _ident,
            ))
        specs += [
            TensorSpec((b, "ln1", "scale"), f"{L}.layernorm_before.weight", _ident, _ident),
            TensorSpec((b, "ln1", "bias"), f"{L}.layernorm_before.bias", _ident, _ident),
            TensorSpec((b, "ln2", "scale"), f"{L}.layernorm_after.weight", _ident, _ident),
            TensorSpec((b, "ln2", "bias"), f"{L}.layernorm_after.bias", _ident, _ident),
            TensorSpec((b, "mlp", "up", "kernel"), f"{L}.intermediate.dense.weight", _t, _t),
            TensorSpec((b, "mlp", "up", "bias"), f"{L}.intermediate.dense.bias", _ident, _ident),
            TensorSpec((b, "mlp", "down", "kernel"), f"{L}.output.dense.weight", _t, _t),
            TensorSpec((b, "mlp", "down", "bias"), f"{L}.output.dense.bias", _ident, _ident),
        ]
    specs += [
        TensorSpec(("ln_final", "scale"), "layernorm.weight", _ident, _ident),
        TensorSpec(("ln_final", "bias"), "layernorm.bias", _ident, _ident),
        TensorSpec(("head", "kernel"), "classifier.weight", _t, _t, optional=True),
        TensorSpec(("head", "bias"), "classifier.bias", _ident, _ident, optional=True),
    ]
    return specs


def vit_config_from_hf(config_json: Dict[str, Any], **overrides: Any):
    """Build a :class:`~unionml_tpu.models.vit.ViTConfig` from an HF
    ``config.json`` dict (checkpoint-faithful: qkv biases + erf GELU)."""
    from unionml_tpu.models.vit import ViTConfig

    act = config_json.get("hidden_act", "gelu")
    if act not in ("gelu", "gelu_new", "gelu_pytorch_tanh"):
        raise NotImplementedError(
            f"hidden_act {act!r} is not supported (gelu variants only)"
        )
    kwargs: Dict[str, Any] = dict(
        image_size=config_json.get("image_size", 224),
        patch_size=config_json.get("patch_size", 16),
        hidden_dim=config_json["hidden_size"],
        num_layers=config_json["num_hidden_layers"],
        num_heads=config_json["num_attention_heads"],
        mlp_dim=config_json["intermediate_size"],
        qkv_bias=config_json.get("qkv_bias", True),
        gelu_exact=(act == "gelu"),
    )
    if config_json.get("id2label"):
        kwargs["num_classes"] = len(config_json["id2label"])
    kwargs.update(overrides)
    return ViTConfig(**kwargs)


def load_vit_checkpoint(
    path: str,
    config: Any = None,
    *,
    dtype: Any = jnp.float32,
    device: Any = None,
    **config_overrides: Any,
) -> Tuple[Dict[str, Any], Any]:
    """Stream an HF ViT safetensors checkpoint into framework params.

    Returns ``(params, config)``. Handles both bare ``ViTModel`` names
    and ``ViTForImageClassification`` checkpoints (``vit.`` prefix +
    ``classifier`` head); without a classifier the ``head`` is absent —
    combine with a fresh init via :func:`merge_pretrained`.
    """
    if config is None:
        cfg_path = os.path.join(path, "config.json") if os.path.isdir(path) else None
        if cfg_path is None or not os.path.exists(cfg_path):
            raise FileNotFoundError(
                "config=None needs a checkpoint DIRECTORY with config.json "
                f"(got {path!r})"
            )
        with open(cfg_path) as f:
            config = vit_config_from_hf(json.load(f), **config_overrides)
    specs = vit_tensor_specs(config)
    reader = _CheckpointReader(path)
    if specs[0].hf_name not in reader and f"vit.{specs[0].hf_name}" in reader:
        import dataclasses

        specs = [
            s if s.hf_name.startswith("classifier")
            else dataclasses.replace(s, hf_name=f"vit.{s.hf_name}")
            for s in specs
        ]
    params = _load_checkpoint(
        path, specs, quantize=False, dtype=dtype, device=device, strict=False,
        reader=reader,
    )
    return params, config


def export_vit_safetensors(
    params: Any,
    config: Any,
    directory: str,
    *,
    max_shard_bytes: Optional[int] = None,
) -> List[str]:
    """Write framework ViT params as an HF-layout checkpoint."""
    config_json = {
        "architectures": ["ViTForImageClassification"],
        "model_type": "vit",
        "image_size": config.image_size,
        "patch_size": config.patch_size,
        "hidden_size": config.hidden_dim,
        "num_hidden_layers": config.num_layers,
        "num_attention_heads": config.num_heads,
        "intermediate_size": config.mlp_dim,
        "qkv_bias": config.qkv_bias,
        "hidden_act": "gelu" if config.gelu_exact else "gelu_pytorch_tanh",
        "id2label": {str(i): str(i) for i in range(config.num_classes)},
    }
    return _export_checkpoint(
        params, vit_tensor_specs(config), directory,
        config_json=config_json, max_shard_bytes=max_shard_bytes,
        skip_missing=True,
    )


# ---------------------------------------------------------------------------
# Checkpoint IO


class _CheckpointReader:
    """Name→shard resolution plus one-tensor-at-a-time reads.

    Accepts a single ``.safetensors`` file, a directory holding one, or a
    sharded HF layout (``model.safetensors.index.json`` → weight_map).
    Reads go through ``safetensors.safe_open`` so only the requested
    tensor's bytes are materialized, never the shard.
    """

    def __init__(self, path: str):
        self._shard_of: Dict[str, str] = {}
        if os.path.isfile(path):
            shards = [path]
        else:
            index = os.path.join(path, "model.safetensors.index.json")
            if os.path.exists(index):
                with open(index) as f:
                    weight_map = json.load(f)["weight_map"]
                self._shard_of = {
                    name: os.path.join(path, shard)
                    for name, shard in weight_map.items()
                }
                shards = []
            else:
                shards = sorted(
                    os.path.join(path, f)
                    for f in os.listdir(path)
                    if f.endswith(".safetensors")
                )
                if not shards:
                    raise FileNotFoundError(
                        f"no .safetensors files or index.json under {path!r}"
                    )
        from safetensors import safe_open

        self._safe_open = safe_open
        for shard in shards:
            with safe_open(shard, framework="numpy") as f:
                for name in f.keys():
                    self._shard_of[name] = shard

    def __contains__(self, name: str) -> bool:
        return name in self._shard_of

    def names(self) -> Sequence[str]:
        return tuple(self._shard_of)

    def read(self, name: str) -> np.ndarray:
        shard = self._shard_of[name]
        with self._safe_open(shard, framework="numpy") as f:
            return f.get_tensor(name)


def _set_path(tree: Dict[str, Any], path: Tuple[str, ...], value: Any) -> None:
    node = tree
    for key in path[:-1]:
        node = node.setdefault(key, {})
    node[path[-1]] = value


def _quantize_on_device(w2d: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    # eager on purpose, NOT jitted: under jit XLA rewrites the /127
    # division into multiply-by-reciprocal, and the 1-ulp scale drift
    # breaks bit-identity with quantize_params (which runs this eagerly)
    from unionml_tpu.models.quantization import _quantize_kernel_2d

    return _quantize_kernel_2d(w2d)


def _load_checkpoint(
    path: str,
    specs: Sequence[TensorSpec],
    *,
    quantize: bool,
    dtype: Any,
    device: Any,
    strict: bool,
    reader: Optional[_CheckpointReader] = None,
    bits: int = 8,
    group_size: int = 0,
    tensor: int = 1,
) -> Dict[str, Any]:
    if reader is None:
        reader = _CheckpointReader(path)
    params: Dict[str, Any] = {}
    missing: List[str] = []
    put = (lambda x: jax.device_put(x, device)) if device is not None else jnp.asarray

    for spec in specs:
        if isinstance(spec, GroupSpec):
            absent = [n for n in spec.hf_names if n not in reader]
            if absent:
                missing.extend(absent)
                continue
            if quantize and spec.quantizable:
                # one expert at a time through the 2D int8 recipe —
                # bit-identical to quantize_params' vmapped form (vmap
                # of the same kernel), with ONE expert tensor resident
                qs, scales = [], []
                for n in spec.hf_names:
                    w = spec.to_framework(reader.read(n))
                    q, scale = _quantize_on_device(
                        put(np.ascontiguousarray(w, np.float32))
                    )
                    qs.append(q)
                    scales.append(scale)
                    del w
                parent, leaf = spec.path[:-1], spec.path[-1]
                _set_path(params, parent + (f"{leaf}_q",), jnp.stack(qs))
                _set_path(params, parent + (f"{leaf}_scale",), jnp.stack(scales))
            else:
                stacked = None
                for e, n in enumerate(spec.hf_names):
                    w = spec.to_framework(reader.read(n))
                    if stacked is None:
                        stacked = np.empty(
                            (len(spec.hf_names),) + w.shape, w.dtype
                        )
                    stacked[e] = w
                    del w
                arr = put(stacked)
                del stacked
                if jnp.issubdtype(arr.dtype, jnp.floating):
                    arr = arr.astype(dtype)
                _set_path(params, spec.path, arr)
            continue
        name = spec.hf_name
        if name not in reader:
            if spec.fallback is not None and spec.fallback in reader:
                name = spec.fallback
            elif spec.optional:
                continue
            else:
                missing.append(spec.hf_name)
                continue
        w = spec.to_framework(reader.read(name))
        if quantize and spec.quantizable:
            # identical K/N geometry to quantize_params: the `o`
            # projection contracts its LEADING dims, everything else its
            # single leading input dim
            k = int(np.prod(w.shape[:-1])) if spec.path[-2] == "o" else w.shape[0]
            w2d = put(np.ascontiguousarray(w, np.float32).reshape(k, -1))
            parent = spec.path[:-1]
            tile = 0
            if bits == 4:
                from unionml_tpu.ops.int4_matmul import (
                    quantize_kernel_int4,
                    tile_for,
                )

                # column-parallel sites pack for the tree's TP degree
                # (quantize_params parity: the tile must divide each
                # device's channel count)
                col_parallel = spec.path[-2] in ("q", "k", "v", "gate", "up")
                tile = tile_for(
                    w2d.shape[1], k, shards=tensor if col_parallel else 1
                )
            if tile and (group_size == 0 or k % group_size == 0):
                # streamed packed-int4 (quantize_params(bits=4) parity;
                # untileable widths fall through to int8 like the
                # in-memory path and the serving module's fallback)
                q, scale = quantize_kernel_int4(
                    w2d, tile, group_size=group_size
                )
                _set_path(params, parent + ("kernel_p",), q)
                _set_path(
                    params,
                    parent + (("scale_g" if group_size else "scale"),),
                    scale,
                )
            else:
                q, scale = _quantize_on_device(w2d)
                _set_path(params, parent + ("kernel_q",), q)
                _set_path(params, parent + ("scale",), scale)
        else:
            arr = put(w)
            if jnp.issubdtype(arr.dtype, jnp.floating) and not spec.keep_dtype:
                arr = arr.astype(dtype)
            _set_path(params, spec.path, arr)
        del w  # one tensor resident at a time — the streaming contract

    if missing:
        raise KeyError(
            f"checkpoint at {path!r} is missing {len(missing)} expected "
            f"tensors (first: {missing[:3]}); wrong config geometry?"
        )
    if strict:
        expected = set()
        for s in specs:
            if isinstance(s, GroupSpec):
                expected.update(s.hf_names)
            else:
                expected.add(s.hf_name)
                if s.fallback:
                    expected.add(s.fallback)
        extra = [n for n in reader.names() if n not in expected]
        if extra:
            raise KeyError(
                f"checkpoint at {path!r} holds {len(extra)} tensors the "
                f"{specs[0].path[0]}-family mapping does not consume "
                f"(first: {extra[:3]}); pass strict=False to ignore"
            )
    return params


def load_llama_checkpoint(
    path: str,
    config: Optional[LlamaConfig] = None,
    *,
    quantize: Optional[bool] = None,
    dtype: Any = jnp.bfloat16,
    device: Any = None,
    strict: bool = False,
    **config_overrides: Any,
) -> Tuple[Dict[str, Any], LlamaConfig]:
    """Stream an HF Llama safetensors checkpoint into framework params.

    Returns ``(params, config)``. With ``config=None`` the geometry is
    read from the checkpoint directory's ``config.json``
    (``config_overrides`` pass through — e.g. ``max_len=8192``).
    ``quantize`` defaults to ``config.quantized``: the result then holds
    quantized trees bit-identical to ``quantize_params(fp_load,
    LLAMA_QUANT_PATTERNS, bits=config.weight_bits)`` without ever
    materializing the fp tree (peak memory ~ one layer's kernel) — int8
    ``kernel_q``+``scale`` by default, packed-int4 ``kernel_p`` when the
    config carries ``weight_bits=4`` (untileable widths fall back to
    int8, mirroring the serving module). Float leaves on the fp path are
    cast to ``dtype`` (serving residency —
    :func:`~unionml_tpu.models.generate.serving_params` semantics).
    """
    if config is None:
        cfg_path = os.path.join(path, "config.json") if os.path.isdir(path) else None
        if cfg_path is None or not os.path.exists(cfg_path):
            raise FileNotFoundError(
                "config=None needs a checkpoint DIRECTORY with config.json "
                f"(got {path!r})"
            )
        with open(cfg_path) as f:
            config = llama_config_from_hf(json.load(f), **config_overrides)
    if quantize is None:
        quantize = config.quantized
    params = _load_checkpoint(
        path, llama_tensor_specs(config),
        quantize=quantize, dtype=dtype, device=device, strict=strict,
        bits=config.weight_bits,
        group_size=config.int4_group, tensor=config.int4_tp,
    )
    return params, config


def load_bert_checkpoint(
    path: str,
    config: Optional[BertConfig] = None,
    *,
    dtype: Any = jnp.float32,
    device: Any = None,
    encoder_key: str = "encoder",
    **config_overrides: Any,
) -> Tuple[Dict[str, Any], BertConfig]:
    """Stream an HF BERT safetensors checkpoint into framework params.

    Returns ``(params, config)`` where ``params`` covers the encoder and
    pooler (task heads are the fine-tune target — combine with a fresh
    init via :func:`merge_pretrained`). Handles both bare ``BertModel``
    tensor names and task-model checkpoints carrying a ``bert.`` prefix.
    """
    if config is None:
        cfg_path = os.path.join(path, "config.json") if os.path.isdir(path) else None
        if cfg_path is None or not os.path.exists(cfg_path):
            raise FileNotFoundError(
                "config=None needs a checkpoint DIRECTORY with config.json "
                f"(got {path!r})"
            )
        with open(cfg_path) as f:
            config = bert_config_from_hf(json.load(f), **config_overrides)
    specs = bert_tensor_specs(config, encoder_key=encoder_key)
    reader = _CheckpointReader(path)
    if specs[0].hf_name not in reader and f"bert.{specs[0].hf_name}" in reader:
        import dataclasses

        specs = [
            dataclasses.replace(s, hf_name=f"bert.{s.hf_name}") for s in specs
        ]
    params = _load_checkpoint(
        path, specs, quantize=False, dtype=dtype, device=device, strict=False,
        reader=reader,
    )
    return params, config


def merge_pretrained(init_params: Any, loaded: Dict[str, Any]) -> Dict[str, Any]:
    """Overlay ``loaded`` pretrained subtrees onto a fresh ``init_params``
    tree (task heads keep their initialization — the fine-tune starting
    point). Raises on a loaded path absent from the init tree: a silent
    drop would fine-tune random weights while reporting success."""
    from collections.abc import Mapping

    def walk(path: Tuple[str, ...], base: Any, over: Any) -> Any:
        if isinstance(over, Mapping):
            if not isinstance(base, Mapping):
                raise KeyError(
                    f"pretrained subtree {'/'.join(path)} has no counterpart "
                    "in the model's param tree (geometry mismatch?)"
                )
            out = dict(base)
            for k, v in over.items():
                if k not in base:
                    raise KeyError(
                        f"pretrained param {'/'.join(path + (k,))} has no "
                        "counterpart in the model's param tree"
                    )
                out[k] = walk(path + (k,), base[k], v)
            return out
        if hasattr(base, "shape") and tuple(base.shape) != tuple(over.shape):
            raise ValueError(
                f"pretrained param {'/'.join(path)} has shape "
                f"{tuple(over.shape)}, model expects {tuple(base.shape)}"
            )
        return over

    return walk((), init_params, loaded)


# ---------------------------------------------------------------------------
# Export (HF-layout writer — the fixture generator and interchange path)


def _export_checkpoint(
    params: Any,
    specs: Sequence[TensorSpec],
    directory: str,
    *,
    config_json: Optional[Dict[str, Any]],
    max_shard_bytes: Optional[int],
    skip_missing: bool = False,
) -> List[str]:
    from safetensors.numpy import save_file

    os.makedirs(directory, exist_ok=True)
    flat: List[Tuple[str, np.ndarray]] = []
    for spec in specs:
        node: Any = params
        try:
            for key in spec.path:
                node = node[key]
        except (KeyError, TypeError):
            if skip_missing:
                continue
            raise KeyError(
                f"param tree is missing {'/'.join(spec.path)} (export specs "
                "must match the tree — was the model built with this config?)"
            )
        w = np.asarray(node)
        if w.dtype == np.dtype("V2"):  # raw bf16 view
            w = w.view(np.uint16)
        if isinstance(spec, GroupSpec):
            for e, hf_name in enumerate(spec.hf_names):
                flat.append((hf_name, spec.to_hf(np.ascontiguousarray(w[e]))))
        else:
            flat.append((spec.hf_name, spec.to_hf(np.ascontiguousarray(w))))

    # shard greedily in spec order so related tensors stay together
    shards: List[List[Tuple[str, np.ndarray]]] = [[]]
    size = 0
    for name, w in flat:
        nbytes = w.nbytes
        if max_shard_bytes and shards[-1] and size + nbytes > max_shard_bytes:
            shards.append([])
            size = 0
        shards[-1].append((name, w))
        size += nbytes
    written: List[str] = []
    if len(shards) == 1:
        out = os.path.join(directory, "model.safetensors")
        save_file(dict(shards[0]), out)
        written.append(out)
    else:
        weight_map: Dict[str, str] = {}
        total = sum(w.nbytes for _, w in flat)
        for i, group in enumerate(shards):
            fname = f"model-{i + 1:05d}-of-{len(shards):05d}.safetensors"
            save_file(dict(group), os.path.join(directory, fname))
            written.append(os.path.join(directory, fname))
            for name, _ in group:
                weight_map[name] = fname
        index = {
            "metadata": {"total_size": total},
            "weight_map": weight_map,
        }
        with open(os.path.join(directory, "model.safetensors.index.json"), "w") as f:
            json.dump(index, f, indent=2)
    if config_json is not None:
        with open(os.path.join(directory, "config.json"), "w") as f:
            json.dump(config_json, f, indent=2)
    return written


def export_llama_safetensors(
    params: Any,
    config: LlamaConfig,
    directory: str,
    *,
    max_shard_bytes: Optional[int] = None,
    tie_lm_head: bool = False,
) -> List[str]:
    """Write framework Llama params as an HF-layout checkpoint.

    ``max_shard_bytes`` splits into an indexed multi-shard layout (HF
    convention); ``tie_lm_head`` omits ``lm_head.weight`` (tied
    checkpoints). Returns the written shard paths. fp trees only — int8
    serving trees have no HF layout to round-trip to.
    """
    specs = llama_tensor_specs(config)
    if tie_lm_head:
        specs = [
            s for s in specs
            if getattr(s, "hf_name", None) != "lm_head.weight"
        ]
    config_json = {
        "architectures": [
            "MixtralForCausalLM" if config.num_experts else "LlamaForCausalLM"
        ],
        "model_type": "mixtral" if config.num_experts else "llama",
        "vocab_size": config.vocab_size,
        "hidden_size": config.hidden_dim,
        "num_hidden_layers": config.num_layers,
        "num_attention_heads": config.num_heads,
        "num_key_value_heads": config.num_kv_heads,
        "intermediate_size": config.mlp_dim,
        "rope_theta": config.rope_theta,
        "rms_norm_eps": config.norm_eps,
        "max_position_embeddings": config.max_len,
        "tie_word_embeddings": tie_lm_head,
    }
    if config.rope_scaling is not None:
        factor, low, high, orig = config.rope_scaling
        config_json["rope_scaling"] = {
            "rope_type": "llama3", "factor": factor,
            "low_freq_factor": low, "high_freq_factor": high,
            "original_max_position_embeddings": orig,
        }
    if config.num_experts:
        config_json["num_local_experts"] = config.num_experts
        config_json["num_experts_per_tok"] = config.num_selected
    return _export_checkpoint(
        params, specs, directory,
        config_json=config_json, max_shard_bytes=max_shard_bytes,
    )


def export_bert_safetensors(
    params: Any,
    config: BertConfig,
    directory: str,
    *,
    max_shard_bytes: Optional[int] = None,
    encoder_key: str = "encoder",
) -> List[str]:
    """Write framework BERT encoder+pooler params as an HF-layout
    checkpoint (task heads are framework-local and are not exported)."""
    specs = bert_tensor_specs(config, encoder_key=encoder_key)
    config_json = {
        "architectures": ["BertModel"],
        "model_type": "bert",
        "vocab_size": config.vocab_size,
        "max_position_embeddings": config.max_len,
        "type_vocab_size": config.num_types,
        "hidden_size": config.hidden_dim,
        "num_hidden_layers": config.num_layers,
        "num_attention_heads": config.num_heads,
        "intermediate_size": config.mlp_dim,
        # bert_config_from_hf defaults a MISSING hidden_act to erf-gelu;
        # omitting it here would silently swap a tanh-gelu BERT's
        # activation on reload (the ViT exporter records it too)
        "hidden_act": "gelu" if config.gelu_exact else "gelu_pytorch_tanh",
    }
    return _export_checkpoint(
        params, specs, directory,
        config_json=config_json, max_shard_bytes=max_shard_bytes,
        skip_missing=True,  # pooler absent from bare-encoder trees
    )
