"""Pipeline-parallel Llama training: GPipe stages over the `pipeline` axis.

SURVEY.md §7.8 makes PP a named strategy; this module wires it into the
model zoo. The decoder's block stack splits into ``num_stages`` runs of
consecutive blocks; each stage's parameters live on one slice of the
``pipeline`` mesh axis, and :func:`unionml_tpu.parallel.pipeline_apply`
runs the differentiable SPMD GPipe schedule (microbatches flow between
stages via ``ppermute`` over ICI, the whole schedule is one jit program).
Embedding and the LM head run outside the pipeline — they are replicated
(or data-sharded) and cheap relative to the block stack.

PP composes with DP: pass ``ShardingConfig(pipeline=n, data=m)``-style
meshes and ``data_axis="data"`` — microbatch rows shard over ``data``
while stage weights shard over ``pipeline``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from unionml_tpu.models.llama import LlamaBlock, LlamaConfig
from unionml_tpu.models.layers import RMSNorm, make_dense
from unionml_tpu.models.train import TrainState, adamw, masked_cross_entropy
from unionml_tpu.parallel.pipeline import pipeline_apply, stack_stage_params
from unionml_tpu.parallel.sharding import PartitionRule

PIPELINE_PARTITION_RULES = (
    # stacked stage params carry a leading stage dim; unanchored so it
    # matches both params/stages/... and opt_state/.../mu/stages/...
    PartitionRule(r"stages/", ("pipeline",)),
)


class LlamaStage(nn.Module):
    """A run of ``num_blocks`` consecutive Llama blocks (one pipeline stage)."""

    config: LlamaConfig
    num_blocks: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for i in range(self.num_blocks):
            x, _ = LlamaBlock(self.config, name=f"block_{i}")(x)
        return x


class _Embedder(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, tokens: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        return nn.Embed(
            cfg.vocab_size, cfg.hidden_dim, dtype=jnp.dtype(cfg.dtype), name="embed"
        )(tokens)


class _Head(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        x = RMSNorm(dtype=jnp.dtype(cfg.dtype), name="final_norm")(x)
        # same bias-free DenseGeneral as Llama's lm_head: param structures
        # stay interchangeable (to_pipeline_params)
        return make_dense(
            quantized=False, features=cfg.vocab_size, dtype=jnp.float32,
            name="lm_head",
        )(x.astype(jnp.float32))


def _modules(cfg: LlamaConfig, num_stages: int):
    if cfg.num_layers % num_stages:
        raise ValueError(
            f"num_layers {cfg.num_layers} not divisible by num_stages {num_stages}"
        )
    if cfg.num_experts:
        raise NotImplementedError(
            "pipelined MoE is not supported: the per-layer aux losses sown "
            "inside shard_map stages cannot reach the loss"
        )
    if cfg.quantized:
        raise NotImplementedError(
            "pipelined training does not support int8 serving quantization"
        )
    per = cfg.num_layers // num_stages
    return _Embedder(cfg), LlamaStage(cfg, per), _Head(cfg)


def create_pipelined_lm_state(
    cfg: LlamaConfig,
    num_stages: int,
    example_tokens: jnp.ndarray,
    *,
    optimizer: Optional[optax.GradientTransformation] = None,
    learning_rate: float = 1e-3,
    weight_decay: float = 0.0,
    seed: int = 0,
) -> TrainState:
    """TrainState whose params are ``{embed, stages, head}``.

    ``stages`` stacks per-stage block params on a leading axis — shard it
    over ``pipeline`` with :data:`PIPELINE_PARTITION_RULES`.
    """
    embedder, stage_module, head = _modules(cfg, num_stages)
    keys = jax.random.split(jax.random.PRNGKey(seed), num_stages + 2)
    x = embedder.init(keys[0], example_tokens)
    h = embedder.apply(x, example_tokens)
    stage_params = [
        stage_module.init(keys[1 + s], h)["params"] for s in range(num_stages)
    ]
    params = {
        "embed": x["params"],
        "stages": stack_stage_params(stage_params),
        "head": head.init(keys[-1], h)["params"],
    }
    tx = optimizer or adamw(learning_rate, weight_decay=weight_decay)
    return TrainState.create(apply_fn=None, params=params, tx=tx)


def to_pipeline_params(flat_params: Any, cfg: LlamaConfig, num_stages: int) -> Any:
    """Regroup a flat :class:`Llama` param tree into the pipelined layout.

    ``block_i`` goes to stage ``i // (L/num_stages)`` as its local
    ``block_{i mod per}``; embed and final_norm/lm_head move to the
    ``embed`` / ``head`` groups. Enables checkpoint migration between the
    serial and pipelined trainers.
    """
    _modules(cfg, num_stages)  # same validation as the trainer path
    per = cfg.num_layers // num_stages
    stages = []
    for s in range(num_stages):
        stages.append({
            f"block_{i}": flat_params[f"block_{s * per + i}"] for i in range(per)
        })
    return {
        "embed": {"embed": flat_params["embed"]},
        "stages": stack_stage_params(stages),
        "head": {
            "final_norm": flat_params["final_norm"],
            "lm_head": flat_params["lm_head"],
        },
    }


def pipelined_lm_apply(
    params: Any,
    tokens: jnp.ndarray,
    cfg: LlamaConfig,
    num_stages: int,
    *,
    mesh,
    num_microbatches: int,
    data_axis: Optional[str] = None,
    remat: bool = True,
) -> jnp.ndarray:
    """Forward logits [B, S, V] through the pipelined decoder."""
    embedder, stage_module, head = _modules(cfg, num_stages)
    h = embedder.apply({"params": params["embed"]}, tokens)
    h = pipeline_apply(
        lambda p, mb: stage_module.apply({"params": p}, mb),
        params["stages"], h,
        mesh=mesh, num_microbatches=num_microbatches,
        data_axis=data_axis, remat=remat,
    )
    return head.apply({"params": params["head"]}, h)


def pipelined_lm_step(
    cfg: LlamaConfig,
    num_stages: int,
    *,
    mesh,
    num_microbatches: int,
    data_axis: Optional[str] = None,
    ignore_id: int = -100,
) -> Callable:
    """``step(state, batch) -> (state, metrics)`` with the block stack
    pipelined (jit this under the mesh, e.g. via ``compile_step`` with
    ``ShardingConfig(pipeline=n, data=m, rules=PIPELINE_PARTITION_RULES)``).
    """

    def step(state: TrainState, batch):
        if isinstance(batch, tuple):
            inputs, targets = batch
        else:
            inputs, targets = batch[:, :-1], batch[:, 1:]

        def loss_fn(params):
            logits = pipelined_lm_apply(
                params, inputs, cfg, num_stages,
                mesh=mesh, num_microbatches=num_microbatches, data_axis=data_axis,
            )
            return masked_cross_entropy(logits, targets, ignore_id=ignore_id)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        state = state.apply_gradients(grads=grads)
        return state, {"loss": loss, "perplexity": jnp.exp(loss)}

    return step
