"""TPU-native model zoo (BASELINE.json configs #2-#5).

The reference has no model zoo — users bring sklearn/torch/keras objects
(reference: unionml/model.py:931-988 detects the framework only to pick a
serializer). The TPU-native framework ships flax modules whose forward and
train steps are jit/pjit programs, each family paired with tensor-parallel
partition rules for :class:`unionml_tpu.parallel.ShardingConfig`.
"""

from unionml_tpu.models.bert import (
    BERT_PARTITION_RULES,
    BertClassifier,
    BertConfig,
    BertEncoder,
    BertMlm,
    make_mlm_batch,
    mlm_step,
)
from unionml_tpu.models.llama import (
    LLAMA_INT4_PARTITION_RULES,
    LLAMA_LORA_PARTITION_RULES,
    LLAMA_MOE_PARTITION_RULES,
    LLAMA_PARTITION_RULES,
    LLAMA_QUANT_PARTITION_RULES,
    Llama,
    LlamaConfig,
    init_cache,
)
from unionml_tpu.models.encdec import (
    ENCDEC_PARTITION_RULES,
    EncDecConfig,
    EncoderDecoder,
    init_decoder_cache,
    make_seq2seq_generator,
    make_seq2seq_predictor,
    seq2seq_step,
)
from unionml_tpu.models.convert import (
    bert_config_from_hf,
    export_bert_safetensors,
    export_llama_safetensors,
    export_vit_safetensors,
    llama_config_from_hf,
    load_bert_checkpoint,
    load_llama_checkpoint,
    load_vit_checkpoint,
    merge_pretrained,
    vit_config_from_hf,
)
from unionml_tpu.models.generate import (
    PrefixCache,
    make_generator,
    make_lm_predictor,
    make_prefix_cache,
    serving_params,
)
from unionml_tpu.models.lora import (
    LORA_PARTITION_RULES,
    LoRADenseGeneral,
    LoRATrainState,
    create_lora_train_state,
    merge_lora,
    merge_param_trees,
    split_lora_params,
)
from unionml_tpu.models.speculative import (
    make_speculative_generator,
    make_speculative_predictor,
)
from unionml_tpu.models.mlp import Mlp, MlpConfig
from unionml_tpu.models.sequence_parallel import (
    sequence_parallel_config,
    sequence_parallel_lm_step,
)
from unionml_tpu.models.pipeline_lm import (
    PIPELINE_PARTITION_RULES,
    create_pipelined_lm_state,
    pipelined_lm_apply,
    pipelined_lm_step,
    to_pipeline_params,
)
from unionml_tpu.models.quantization import LLAMA_QUANT_PATTERNS, QuantizedDenseGeneral, quantize_params
from unionml_tpu.models.train import (
    GradOverlap,
    TrainState,
    adamw,
    classification_step,
    create_train_state,
    grad_overlap_scope,
    lm_step,
    make_evaluator,
    make_predictor,
)
from unionml_tpu.models.vit import VIT_PARTITION_RULES, ViT, ViTConfig

__all__ = [
    "Mlp", "MlpConfig",
    "ViT", "ViTConfig", "VIT_PARTITION_RULES",
    "BertEncoder", "BertClassifier", "BertMlm", "BertConfig",
    "BERT_PARTITION_RULES", "make_mlm_batch", "mlm_step",
    "Llama", "LlamaConfig", "init_cache", "LLAMA_PARTITION_RULES",
    "EncoderDecoder", "EncDecConfig", "ENCDEC_PARTITION_RULES",
    "init_decoder_cache", "make_seq2seq_generator", "make_seq2seq_predictor", "seq2seq_step",
    "LLAMA_QUANT_PARTITION_RULES", "LLAMA_MOE_PARTITION_RULES",
    "LLAMA_INT4_PARTITION_RULES",
    "LLAMA_LORA_PARTITION_RULES", "LORA_PARTITION_RULES",
    "LoRADenseGeneral", "LoRATrainState", "create_lora_train_state",
    "merge_lora", "merge_param_trees", "split_lora_params",
    "TrainState", "create_train_state", "classification_step", "lm_step",
    "GradOverlap", "grad_overlap_scope",
    "make_evaluator", "make_predictor",
    "make_speculative_generator", "make_speculative_predictor",
    "make_generator", "make_lm_predictor", "serving_params", "adamw",
    "make_prefix_cache", "PrefixCache",
    "create_pipelined_lm_state", "pipelined_lm_step", "pipelined_lm_apply",
    "to_pipeline_params", "PIPELINE_PARTITION_RULES",
    "sequence_parallel_config", "sequence_parallel_lm_step",
    "QuantizedDenseGeneral", "quantize_params", "LLAMA_QUANT_PATTERNS",
]
