"""BERT-style encoder — the remote fine-tune config (BASELINE.json
config #4, "BERT-base fine-tune via remote backend on TPU VM slice").

Encoder with learned positions, GELU MLP, post-LN blocks; heads for
sequence classification (fine-tune) and masked-LM (pretrain parity).
Padding is handled with an attention bias built from the input mask —
static shapes throughout so XLA compiles one program per bucket.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp
from flax import linen as nn

from unionml_tpu.models.layers import MlpBlock
from unionml_tpu.ops.attention import mha_reference
from unionml_tpu.parallel.sharding import PartitionRule


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    max_len: int = 512
    num_types: int = 2
    hidden_dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    num_classes: int = 2  # classification head width
    attn_impl: str = "xla"  # "fused" only when attention_mask is None
    # HF BERT checkpoints use erf GELU; the default tanh approximation is
    # one transcendental cheaper. Checkpoint loaders set True
    # (models/convert.py) for faithful pretrained inference.
    gelu_exact: bool = False
    dtype: str = "bfloat16"

    @staticmethod
    def base(num_classes: int = 2) -> "BertConfig":
        return BertConfig(num_classes=num_classes)

    @staticmethod
    def tiny(vocab_size: int = 1024, num_classes: int = 2) -> "BertConfig":
        return BertConfig(
            vocab_size=vocab_size, max_len=128, hidden_dim=64,
            num_layers=2, num_heads=4, mlp_dim=128, num_classes=num_classes,
        )


class BertBlock(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray, bias: Optional[jnp.ndarray]) -> jnp.ndarray:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        head_dim = cfg.hidden_dim // cfg.num_heads
        dense = lambda feats, name: nn.DenseGeneral(  # noqa: E731
            features=feats, axis=-1, dtype=dtype, name=name
        )
        q = dense((cfg.num_heads, head_dim), "attn_q")(x)
        k = dense((cfg.num_heads, head_dim), "attn_k")(x)
        v = dense((cfg.num_heads, head_dim), "attn_v")(x)
        from unionml_tpu.models.layers import ATTN_IMPLS

        # BERT has no sequence mesh axis: the sequence-parallel impls can
        # never work here
        supported = tuple(
            i for i in ATTN_IMPLS if i not in ("ring", "ring_flash", "ulysses")
        )
        if cfg.attn_impl not in supported:
            raise ValueError(
                f"unknown attention impl {cfg.attn_impl!r}; use one of {supported}"
            )
        if bias is not None:
            # only the XLA reference takes an additive mask bias (padded
            # batches); other impls would silently ignore the padding
            attn = mha_reference(q, k, v, bias=bias)
        else:
            from unionml_tpu.models.layers import _run_attention

            attn = _run_attention(
                q, k, v, impl=cfg.attn_impl, causal=False, sequence_axis=None
            )
        attn = nn.DenseGeneral(
            features=cfg.hidden_dim, axis=(-2, -1), dtype=dtype, name="attn_o"
        )(attn)
        x = nn.LayerNorm(dtype=dtype, name="ln1")(x + attn)
        h = MlpBlock(
            hidden_dim=cfg.mlp_dim, gelu_approximate=not cfg.gelu_exact,
            dtype=dtype, name="mlp",
        )(x)
        return nn.LayerNorm(dtype=dtype, name="ln2")(x + h)


class BertEncoder(nn.Module):
    config: BertConfig = field(default_factory=BertConfig)

    @nn.compact
    def __call__(
        self,
        input_ids: jnp.ndarray,
        *,
        attention_mask: Optional[jnp.ndarray] = None,
        token_type_ids: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        seq = input_ids.shape[1]
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_dim, dtype=dtype, name="tok_embed")
        x = embed(input_ids)
        x = x + nn.Embed(cfg.max_len, cfg.hidden_dim, dtype=dtype, name="pos_embed")(
            jnp.arange(seq)[None, :]
        )
        if token_type_ids is not None:
            x = x + nn.Embed(cfg.num_types, cfg.hidden_dim, dtype=dtype, name="type_embed")(
                token_type_ids
            )
        x = nn.LayerNorm(dtype=dtype, name="ln_embed")(x)
        bias = None
        if attention_mask is not None:
            bias = jnp.where(attention_mask[:, None, None, :].astype(bool), 0.0, -1e30)
        for i in range(cfg.num_layers):
            x = BertBlock(cfg, name=f"block_{i}")(x, bias)
        return x


class BertClassifier(nn.Module):
    """[CLS]-pooled sequence classification (the fine-tune config)."""

    config: BertConfig = field(default_factory=BertConfig)

    @nn.compact
    def __call__(self, input_ids, *, attention_mask=None, token_type_ids=None):
        x = BertEncoder(self.config, name="encoder")(
            input_ids, attention_mask=attention_mask, token_type_ids=token_type_ids
        )
        pooled = nn.tanh(nn.Dense(self.config.hidden_dim, dtype=jnp.float32, name="pooler")(
            x[:, 0].astype(jnp.float32)
        ))
        return nn.Dense(self.config.num_classes, dtype=jnp.float32, name="head")(pooled)


class BertMlm(nn.Module):
    """Masked-LM head over the encoder (pretraining parity)."""

    config: BertConfig = field(default_factory=BertConfig)

    @nn.compact
    def __call__(self, input_ids, *, attention_mask=None):
        cfg = self.config
        x = BertEncoder(cfg, name="encoder")(input_ids, attention_mask=attention_mask)
        x = nn.gelu(nn.Dense(cfg.hidden_dim, dtype=jnp.float32, name="mlm_dense")(
            x.astype(jnp.float32)
        ), approximate=True)
        x = nn.LayerNorm(name="mlm_ln")(x)
        return nn.Dense(cfg.vocab_size, dtype=jnp.float32, name="mlm_head")(x)


def mlm_step(module, *, ignore_id: int = -100, accumulate_steps: int = 1):
    """Masked-LM training step over padded corpora.

    ``batch = (inputs, labels, attention_mask)``: unlike the bare
    ``lm_step(BertMlm(cfg))`` composition (fine for fixed-length
    batches), this passes the padding mask through to the encoder so
    real tokens never attend pad positions. ``accumulate_steps > 1``
    adds gradient accumulation over a leading microbatch axis.
    """
    import jax

    from unionml_tpu.models.train import (
        _bind_frozen,
        accumulated_value_and_grad,
        masked_cross_entropy,
    )

    def loss_fn(params, microbatch):
        inputs, labels, attention_mask = microbatch
        logits = module.apply(
            {"params": params}, inputs, attention_mask=attention_mask
        )
        loss = masked_cross_entropy(logits, labels, ignore_id=ignore_id)
        return loss, {"z": jnp.float32(0.0)}

    def step(state, batch):
        bound = _bind_frozen(loss_fn, state)
        if accumulate_steps > 1:
            (loss, _), grads = accumulated_value_and_grad(
                bound, state.params, batch
            )
        else:
            (loss, _), grads = jax.value_and_grad(bound, has_aux=True)(
                state.params, batch
            )
        state = state.apply_gradients(grads=grads)
        return state, {"loss": loss, "perplexity": jnp.exp(loss)}

    return step


def make_mlm_batch(
    tokens,
    *,
    mask_id: int,
    vocab_size: int,
    rng,
    mask_prob: float = 0.15,
    special_ids: tuple = (0,),
    ignore_id: int = -100,
):
    """BERT masking rule over a token batch: returns ``(inputs, labels)``.

    15% of non-special positions are selected; of those 80% become
    ``mask_id``, 10% a random token, 10% stay unchanged. ``labels``
    carry the original ids at selected positions and ``ignore_id``
    elsewhere — exactly the ``(inputs, labels)`` tuple contract of
    :func:`unionml_tpu.models.train.lm_step`, so MLM pretraining is
    ``lm_step(BertMlm(cfg))`` over these batches. Host-side numpy (runs
    in the data path, not the compiled step); ``rng`` is a
    ``numpy.random.Generator``.
    """
    import numpy as np

    # signed dtype: with uint token arrays (typical tokenized corpora),
    # ignore_id=-100 would wrap to a huge in-range positive and every
    # position would be supervised with a garbage label
    tokens = np.asarray(tokens).astype(np.int64)
    maskable = ~np.isin(tokens, np.asarray(special_ids))
    selected = (rng.random(tokens.shape) < mask_prob) & maskable
    labels = np.where(selected, tokens, ignore_id)
    roll = rng.random(tokens.shape)
    inputs = tokens.copy()
    inputs[selected & (roll < 0.8)] = mask_id
    random_slots = selected & (roll >= 0.8) & (roll < 0.9)
    inputs[random_slots] = rng.integers(0, vocab_size, size=int(random_slots.sum()))
    return inputs, labels


BERT_PARTITION_RULES = (
    PartitionRule(r"attn_(q|k|v)/kernel$", (None, "tensor", None)),
    PartitionRule(r"attn_o/kernel$", ("tensor", None, None)),
    PartitionRule(r"mlp/up/kernel$", (None, "tensor")),
    PartitionRule(r"mlp/down/kernel$", ("tensor", None)),
    PartitionRule(r"tok_embed/embedding$", (None, "tensor")),
    PartitionRule(r"mlm_head/kernel$", (None, "tensor")),
)
