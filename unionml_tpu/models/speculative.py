"""Speculative decoding: draft proposes, target verifies in one forward.

No reference counterpart (the reference has no generation loop at all);
this is the standard latency optimization for autoregressive serving: a
small DRAFT model greedily proposes ``k`` tokens, the TARGET model
scores all ``k + 1`` positions in ONE forward, and the longest prefix
of draft tokens matching the target's own greedy choices is accepted —
plus the target's next token as a free correction/extension. With the
greedy acceptance rule the output is **token-identical to plain greedy
decoding of the target** (tested), so speculation is purely a latency
knob: each accepted draft token replaces one full target decode step
with its share of one batched verify forward.

TPU-first design:

- the whole generation is ONE jitted ``lax.while_loop`` — no host round
  trips per round (through a tunneled backend a round trip costs more
  than an 8B decode step, BASELINE.md round 3);
- per-row acceptance counts differ, so both caches advance by per-row
  amounts — the vector ``cache_index`` path of
  :class:`~unionml_tpu.models.layers.Attention` (built for the
  continuous-batching engine) makes the ``[B, k+1]`` verify forward a
  single program with per-row write offsets;
- rejected draft rows become stale cache entries ABOVE each row's fill;
  visibility follows ``kv_pos <= q_pos`` from the per-row index, and
  every stale row is rewritten by the next round's forward (which
  always covers ``fill .. fill+k``) before it could become visible;
- static shapes throughout: the draft scan is ``k`` fixed steps, the
  verify is ``k + 1`` tokens, and the while_loop trip count is
  data-dependent (fine for inference — no reverse-mode through it),
  bounded by ``max_new_tokens`` rounds since every live row emits at
  least one token per round.

Greedy only: sampled speculative decoding needs the rejection-sampling
correction to keep the target distribution; the greedy rule is exact
and is what the equality tests pin down.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from unionml_tpu.models.llama import Llama, init_cache
from unionml_tpu.models.train import resolve_params

__all__ = [
    "greedy_acceptance",
    "make_speculative_generator",
    "make_speculative_predictor",
]


def greedy_acceptance(proposals: jnp.ndarray, greedy: jnp.ndarray):
    """The greedy acceptance rule — ONE home (this generator's round body
    and the DecodeEngine's speculative round both trace it; a desync
    breaks their shared token-identity-with-plain-greedy contract).

    ``proposals`` [B, k] (draft tokens), ``greedy`` [B, k+1] (the
    target's argmax at each verify position). Draft token i is accepted
    iff it equals the target's choice after position i-1 AND every
    earlier proposal was accepted. Returns ``(accepted [B], correction
    [B], emit [B, k+1])`` — the count of accepted draft tokens, the
    target's next token after the accepted prefix (free
    correction/extension), and the emission buffer holding the accepted
    prefix with the correction at position ``accepted``.
    """
    batch, k = proposals.shape
    rows = jnp.arange(batch)
    match = proposals == greedy[:, :k]
    accepted = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)
    correction = jnp.take_along_axis(greedy, accepted[:, None], axis=1)[:, 0]
    emit = jnp.concatenate(
        [proposals, jnp.zeros((batch, 1), jnp.int32)], axis=1
    )
    emit = emit.at[rows, accepted].set(correction)
    return accepted, correction, emit


def make_speculative_generator(
    target: Llama,
    draft: Llama,
    *,
    max_new_tokens: int,
    speculate_k: int = 4,
    max_len: Optional[int] = None,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
    with_stats: bool = False,
) -> Callable:
    """Build ``generate(target_params, draft_params, tokens) ->
    tokens [B, max_new_tokens]`` (greedy, == plain target decoding).

    ``tokens``: int32 [B, prompt_len], equal lengths (bucket upstream —
    the :func:`~unionml_tpu.models.generate.make_lm_predictor` pattern).
    ``target`` and ``draft`` must share the vocabulary; the draft is
    typically 4-10x smaller, and a round costs ``k + 1`` draft steps
    (the extra step consumes the last proposal so the draft cache stays
    hole-free across fully-accepted rounds) plus one (k+1)-token target
    forward, for ``accepted + 1`` emitted tokens — profitable when the
    draft is much cheaper than the target and acceptance is high.

    ``with_stats=True``: returns ``(tokens, {"rounds": [..],
    "accepted": [..]})`` per batch row — rounds taken and total draft
    tokens accepted (the acceptance-rate observability the equality
    tests can't see).

    ``generate`` also takes an optional ``true_lens`` int vector [B] for
    RIGHT-padded prompt batches (the serving-bucket form): each row's
    caches fill only to its true length, the first token reads that
    row's last REAL position, and the pad-garbage cache rows sit above
    the fill where visibility (``kv_pos <= q_pos``) cannot reach them
    before a later round overwrites them (fill advances ≤ k+1 per round
    while rounds write ``fill..fill+k`` — no row can be skipped).
    """
    t_cfg, d_cfg = target.config, draft.config
    if t_cfg.vocab_size != d_cfg.vocab_size:
        raise ValueError(
            f"target/draft vocabularies differ: {t_cfg.vocab_size} vs "
            f"{d_cfg.vocab_size}"
        )
    k = int(speculate_k)
    if k < 1:
        raise ValueError(f"speculate_k must be >= 1, got {k}")

    def generate(
        target_params, draft_params, tokens: jnp.ndarray, true_lens=None
    ) -> jnp.ndarray:
        batch, prompt_len = tokens.shape
        # + k + 1 slack: a round writes up to k+1 rows past a row's fill
        # before acceptance truncates it
        total = (max_len or (prompt_len + max_new_tokens)) + k + 1
        rows = jnp.arange(batch)

        # prefill BOTH models on the full prompt; each row's fill counts
        # cache rows written, and the last emitted token is consumed by
        # the NEXT forward (standard KV bookkeeping)
        t_cache = init_cache(t_cfg, batch, total)
        d_cache = init_cache(d_cfg, batch, total)
        if true_lens is None:
            true_lens = jnp.full((batch,), prompt_len, jnp.int32)
        else:
            true_lens = jnp.asarray(true_lens, jnp.int32)
        # head on each row's last REAL position only (logit_index): the
        # full-sequence head would materialize [B, S, vocab] fp32 — the
        # same last-position trick the plain generator uses (causal
        # prefill: positions < true_len never attend the right-padding)
        t_logits, t_cache = target.apply(
            {"params": target_params}, tokens, cache=t_cache,
            cache_index=jnp.int32(0), logit_index=true_lens - 1,
        )
        # the draft's prefill logits are never read: logit_index=0 makes
        # the head a [B, 1, V] stub that XLA dead-code-eliminates
        _, d_cache = draft.apply(
            {"params": draft_params}, tokens, cache=d_cache,
            cache_index=jnp.int32(0),
            logit_index=jnp.zeros((batch,), jnp.int32),
        )
        first = jnp.argmax(t_logits[:, 0], -1).astype(jnp.int32)  # [B]

        out = jnp.full((batch, max_new_tokens + k + 1), pad_id, jnp.int32)
        out = out.at[:, 0].set(first)
        fill0 = true_lens
        done0 = jnp.full((batch,), max_new_tokens <= 1)
        if eos_id is not None:
            done0 = done0 | (first == eos_id)
        emitted0 = jnp.ones((batch,), jnp.int32)

        def body(carry):
            t_cache, d_cache, out, fill, last, done, emitted, rounds, acc_total = carry

            # ---- draft proposes k greedy tokens (k+1 tiny scan steps:
            # the extra step consumes proposal k, writing its KV so a
            # fully-accepted round leaves NO hole at row fill+k — the
            # next round's draft queries would otherwise attend a
            # zero-filled slot and acceptance would collapse) ----
            def draft_step(c, _):
                cache, tok, f = c
                logits, cache = draft.apply(
                    {"params": draft_params}, tok[:, None], cache=cache,
                    cache_index=f,
                )
                nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
                return (cache, nxt, f + 1), nxt

            (d_cache, _, _), proposals = jax.lax.scan(
                draft_step, (d_cache, last, fill), None, length=k + 1
            )
            proposals = proposals.T[:, :k]                     # [B, k]

            # ---- target verifies [last, d_1..d_k] in one forward ----
            verify_in = jnp.concatenate([last[:, None], proposals], axis=1)
            v_logits, t_cache = target.apply(
                {"params": target_params}, verify_in, cache=t_cache,
                cache_index=fill,
            )
            greedy = jnp.argmax(v_logits, -1).astype(jnp.int32)  # [B, k+1]
            accepted, correction, emit_toks = greedy_acceptance(
                proposals, greedy
            )
            emit_len = jnp.where(done, 0, accepted + 1)        # [B]

            # write this round's tokens at each row's emitted offset
            pos = emitted[:, None] + jnp.arange(k + 1)[None, :]  # [B, k+1]
            valid = jnp.arange(k + 1)[None, :] < emit_len[:, None]
            if eos_id is not None:
                # nothing after the first eos of the round is emitted
                is_eos = emit_toks == eos_id
                after_eos = jnp.cumsum(
                    jnp.pad(is_eos, ((0, 0), (1, 0)))[:, :-1], axis=1
                ) > 0
                valid = valid & ~after_eos
            emit_count = valid.sum(axis=1).astype(jnp.int32)
            safe_pos = jnp.where(valid, pos, out.shape[1] - 1)
            out = out.at[rows[:, None], safe_pos].set(
                jnp.where(valid, emit_toks, out[rows[:, None], safe_pos])
            )

            new_fill = jnp.where(done, fill, fill + accepted + 1)
            new_last = jnp.where(done, last, correction)
            new_emitted = emitted + emit_count
            new_done = done | (new_emitted >= max_new_tokens)
            if eos_id is not None:
                new_done = new_done | (valid & (emit_toks == eos_id)).any(axis=1)
            new_rounds = rounds + jnp.where(done, 0, 1)
            new_acc = acc_total + jnp.where(done, 0, accepted)
            return (
                t_cache, d_cache, out, new_fill, new_last, new_done,
                new_emitted, new_rounds, new_acc,
            )

        def cond(carry):
            done = carry[5]
            return ~done.all()

        zeros = jnp.zeros((batch,), jnp.int32)
        carry = (t_cache, d_cache, out, fill0, first, done0, emitted0, zeros, zeros)
        carry = jax.lax.while_loop(cond, body, carry)
        toks = carry[2][:, :max_new_tokens]
        if with_stats:
            return toks, {"rounds": carry[7], "accepted": carry[8]}
        return toks

    return jax.jit(generate)


def make_speculative_predictor(
    target: Llama,
    draft: Llama,
    *,
    max_new_tokens: int = 32,
    bucket_lens: tuple = (16, 32, 64, 128),
    speculate_k: int = 4,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
) -> Callable:
    """An ``@model.predictor``-compatible fn with speculative decoding.

    The serving-side wrapper, matching ``make_lm_predictor``'s shape
    discipline: ragged token-id prompts are RIGHT-padded to the smallest
    covering prompt bucket and the batch to the next power of two, so
    XLA compiles a bounded executable set — one generator call per
    request, per-row ``true_lens`` keeping padded rows exact (the
    generator's vector-fill prefill). ``state`` must carry the paired
    trees as a mapping ``{"target": ..., "draft": ...}`` (plain dict or
    ``flax.core.FrozenDict``; or an object with ``.params`` holding it)
    — the artifact a speculative serving app saves. Output trims at
    ``eos_id`` when set.

    ``.warmup(state, max_batch=...)`` pre-compiles every (bucket,
    power-of-two batch) executable, like the LM predictor's.
    """
    from collections.abc import Mapping

    import numpy as np

    buckets = tuple(sorted(set(int(b) for b in bucket_lens)))
    gens = {
        b: make_speculative_generator(
            target, draft, max_new_tokens=max_new_tokens, speculate_k=speculate_k,
            max_len=b + max_new_tokens, eos_id=eos_id, pad_id=pad_id,
        )
        for b in buckets
    }

    def predictor(state, prompts) -> list:
        params = resolve_params(state)
        if (
            not isinstance(params, Mapping)
            or "target" not in params
            or "draft" not in params
        ):
            raise ValueError(
                'speculative predictor state must be a mapping '
                '{"target": params, "draft": params}'
            )
        rows = [np.asarray(p, dtype=np.int32).ravel() for p in prompts]
        if any(len(r) == 0 for r in rows):
            raise ValueError("empty prompt")
        longest = max(len(r) for r in rows)
        bucket = next((b for b in buckets if b >= longest), None)
        if bucket is None:
            raise ValueError(
                f"prompt length {longest} exceeds the largest bucket "
                f"{buckets[-1]}; add a larger bucket to bucket_lens"
            )
        n = len(rows)
        n_padded = 1 << (n - 1).bit_length()
        batch = np.full((n_padded, bucket), pad_id, np.int32)
        true_lens = np.ones((n_padded,), np.int32)
        for i in range(n_padded):
            r = rows[min(i, n - 1)]               # pad rows replicate last
            batch[i, : len(r)] = r
            true_lens[i] = len(r)
        out = np.asarray(
            gens[bucket](
                params["target"], params["draft"], jnp.asarray(batch),
                jnp.asarray(true_lens),
            )
        )
        results = []
        for row in out[:n]:
            toks = row.tolist()
            if eos_id is not None and eos_id in toks:
                toks = toks[: toks.index(eos_id) + 1]
            results.append(toks)
        return results

    def warmup(state, *, max_batch: int = 8, buckets: Optional[tuple] = None,
               _all=buckets) -> int:
        if buckets is not None and not buckets:
            raise ValueError(
                "warmup got an empty bucket tuple — pass buckets=None to "
                "warm every configured bucket"
            )
        use = _all if buckets is None else tuple(buckets)
        unknown = sorted(set(use) - set(_all))
        if unknown:
            raise ValueError(
                f"warmup buckets {unknown} are not configured ({_all})"
            )
        compiled = 0
        top = 1 << (max(1, max_batch) - 1).bit_length()
        for b in use:
            size = 1
            while size <= top:
                predictor(state, np.ones((size, b), np.int32))
                compiled += 1
                size *= 2
        return compiled

    predictor.warmup = warmup
    return predictor
