"""Weight-only int8 quantization for serving.

No reference counterpart (the reference serves whatever sklearn/torch
object was trained — reference: unionml/fastapi.py:50-64). On TPU,
autoregressive decode is HBM-bandwidth-bound on *parameter reads* (every
generated token streams the full weight set through the MXU), so storing
matmul weights as int8 with per-output-channel fp scales roughly halves
decode latency versus bf16: XLA fuses the int8→bf16 convert into the
matmul, so HBM traffic is the int8 bytes. Quality: symmetric per-channel
weight-only int8 is the standard "free lunch" point — activations stay
bf16, no calibration data needed.

Two pieces:

- :class:`QuantizedDenseGeneral` — drop-in for the dense projections in
  :mod:`unionml_tpu.models.layers` (same ``(axis, features)`` geometry),
  storing ``kernel_q`` int8 ``[K, N]`` + ``scale`` fp32 ``[N]``.
- :func:`quantize_params` — convert a trained fp param tree into the
  quantized module's param structure (kernels reshaped to 2D, quantized
  per output channel; everything else passed through).

Llama opts in with ``LlamaConfig(quantized=True)`` — the same weights
trained unquantized load after :func:`quantize_params`.
"""

from __future__ import annotations

import re
from typing import Any, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn


def _dense_geometry(x, axis, features):
    """Shared DenseGeneral geometry: normalize contraction axes, flatten
    the input to ``[..., K]`` and report ``(xt, lead, feats, k, n)``."""
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    axes = tuple(a % x.ndim for a in axes)
    feats = (features,) if isinstance(features, int) else tuple(features)
    k = int(np.prod([x.shape[a] for a in axes]))
    n = int(np.prod(feats))
    batch_axes = tuple(i for i in range(x.ndim) if i not in axes)
    xt = x.transpose(*batch_axes, *axes).reshape(
        tuple(x.shape[i] for i in batch_axes) + (k,)
    )
    return xt, xt.shape[:-1], feats, k, n


class QuantizedDenseGeneral(nn.Module):
    """Weight-only int8 dense layer matching DenseGeneral geometry.

    ``axis``: input dims to contract (int or tuple, negative indices);
    ``features``: output dims (int or tuple). The kernel is stored 2D
    ``[K, N]`` int8 with a per-output-channel fp32 ``scale`` ``[N]``.
    """

    features: Union[int, Sequence[int]]
    axis: Union[int, Sequence[int]] = -1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        xt, lead, feats, k, n = _dense_geometry(x, self.axis, self.features)
        kernel_q = self.param(
            "kernel_q", nn.initializers.zeros, (k, n), jnp.int8
        )
        scale = self.param("scale", nn.initializers.ones, (n,), jnp.float32)
        # int8 weights convert to the compute dtype inside the fused
        # matmul: HBM reads stay int8
        w = kernel_q.astype(self.dtype)
        y = jax.lax.dot_general(
            xt.astype(self.dtype), w,
            (((xt.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        y = (y * scale).astype(self.dtype)
        return y.reshape(lead + feats)


class Int4DenseGeneral(nn.Module):
    """Weight-only packed-int4 dense layer (DenseGeneral geometry).

    Stores ``kernel_p`` int8 ``[K, N/2]`` (two nibbles per byte, the
    tile-slab order of :mod:`unionml_tpu.ops.int4_matmul`) + fp32
    ``scale [N]`` — or ``scale_g [K/group_size, N]`` when ``group_size``
    is set (group-wise scales, the 4-bit quality recipe; the distinct
    name keeps the 2D leaf's partition rules separate from the 1D
    scale's). Decode-sized row counts run the Pallas kernel so HBM
    weight reads stay at the packed width — measured 1.54x over int8 on
    the streamed MLP probe (BASELINE.md round 4); other shapes take the
    XLA unpack path with identical semantics.

    ``shards``: the tensor-parallel degree the packing tile must
    survive (``tile_for``'s shard-aligned slab rule) — set it on
    COLUMN-parallel sites (q/k/v, gate/up) when the tree is packed for
    TP; row-parallel sites (o, down, the K-sharded lm_head) keep 1.
    MUST match the ``tensor=`` the tree was quantized with, or the
    baked slab order and the layer's tile disagree and decode produces
    garbage (guarded by ``assert_int4_tp_compatible``).
    """

    features: Union[int, Sequence[int]]
    axis: Union[int, Sequence[int]] = -1
    dtype: Any = jnp.bfloat16
    group_size: int = 0
    shards: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        from unionml_tpu.ops.int4_matmul import int4_matmul, tile_for

        xt, lead, feats, k, n = _dense_geometry(x, self.axis, self.features)
        tile = tile_for(n, k, shards=self.shards)
        if tile == 0 or (self.group_size and k % self.group_size):
            # untileable width (odd N, VMEM-oversized single tile) or a
            # K-group that doesn't divide this layer's contraction: the
            # SAME per-layer int8 fallback quantize_params(bits=4)
            # applies — param structure and math match kernel_q+scale,
            # so a mixed int4/int8 tree loads as one module family
            kernel_q = self.param(
                "kernel_q", nn.initializers.zeros, (k, n), jnp.int8
            )
            scale = self.param("scale", nn.initializers.ones, (n,), jnp.float32)
            y = jax.lax.dot_general(
                xt.astype(self.dtype), kernel_q.astype(self.dtype),
                (((xt.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return ((y * scale).astype(self.dtype)).reshape(lead + feats)
        kernel_p = self.param(
            "kernel_p", nn.initializers.zeros, (k, n // 2), jnp.int8
        )
        if self.group_size:
            scale = self.param(
                "scale_g", nn.initializers.ones,
                (k // self.group_size, n), jnp.float32,
            )
        else:
            scale = self.param("scale", nn.initializers.ones, (n,), jnp.float32)
        y = int4_matmul(
            xt.reshape(-1, k), kernel_p, scale, tile_n=tile,
            dtype=self.dtype, group_size=self.group_size,
        )
        return y.reshape(lead + feats)


def _quantize_kernel_2d(w2d: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-output-channel int8: returns (kernel_q, scale)."""
    w = jnp.asarray(w2d, jnp.float32)
    absmax = jnp.max(jnp.abs(w), axis=0)                       # [N]
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _quantize_expert_kernel(w3d: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-(expert, out-channel) symmetric int8 for [E, K, N] MoE weights:
    the 2D recipe vmapped over the leading expert axis."""
    return jax.vmap(_quantize_kernel_2d)(jnp.asarray(w3d))


LLAMA_QUANT_PATTERNS = (
    r"attn/(q|k|v|o)$", r"mlp/(gate|up|down)$", r"lm_head$", r"moe$"
)


def quantize_params(
    params: Any,
    patterns: Sequence[str],
    *,
    bits: int = 8,
    group_size: int = 0,
    tensor: int = 1,
) -> Any:
    """Convert fp dense kernels to the quantized param structure.

    ``bits=4`` produces the packed-int4 layout (``kernel_p`` + ``scale``
    — :class:`Int4DenseGeneral`) for matching DENSE kernels; MoE expert
    blocks stay int8 (no int4 expert kernel). Layers with an odd output
    width also stay int8.

    ``group_size`` (bits=4 only): group-wise scales ``scale_g [K/g, N]``
    instead of per-channel ``[N]`` — the 4-bit quality recipe. The model
    config must carry the same ``int4_group`` so the module declares the
    matching leaf.

    ``tensor`` (bits=4 only): the tensor-parallel degree to pack for —
    COLUMN-parallel sites (q/k/v, gate/up) bake a tile dividing their
    per-device channel count so a ``tensor``-axis shard of the packed
    columns stays a valid slab packing (row-parallel o/down and the
    K-sharded lm_head are unaffected). The model config must carry the
    same ``int4_tp``.

    ``patterns`` is required (use :data:`LLAMA_QUANT_PATTERNS` for the
    Llama zoo model): a catch-all would silently mis-split kernels whose
    geometry this name-based dispatch doesn't know (e.g. BERT's
    ``attn_o``, ViT's 4D patch-embed conv).

    Walks the tree; any dict holding a ``kernel`` whose path matches one
    of ``patterns`` becomes ``{"kernel_q": int8 [K, N], "scale": [N]}``.
    The K/N split follows the layer geometry in
    :mod:`unionml_tpu.models.layers`: a projection named ``o`` contracts
    its LEADING dims (``[heads, dim, out]`` → K=heads*dim, N=out); every
    other projection contracts its single leading input dim
    (``[in, ...features]`` → K=in, N=prod(features)). A module with a
    differently-shaped multi-axis kernel needs its own conversion — this
    name-based dispatch covers the shipped model zoo only.
    Non-matching subtrees pass through unchanged.
    """
    compiled = [re.compile(p) for p in patterns]

    def walk(path, tree):
        if isinstance(tree, dict) and "w_gate" in tree and "w_down" in tree:
            # MoE expert block (ops/moe.py): [E, K, N] weights quantize
            # per (expert, out-channel); the fp32 router passes through
            joined = "/".join(path)
            if any(c.search(joined) for c in compiled):
                out = {}
                for name, v in tree.items():
                    if name in ("w_gate", "w_up", "w_down"):
                        q, scale = _quantize_expert_kernel(jnp.asarray(v))
                        out[f"{name}_q"] = q
                        out[f"{name}_scale"] = scale
                    else:
                        out[name] = v
                return out
        if isinstance(tree, dict) and "kernel" in tree and isinstance(
            tree["kernel"], (jnp.ndarray, np.ndarray)
        ):
            joined = "/".join(path)
            if any(c.search(joined) for c in compiled):
                w = jnp.asarray(tree["kernel"])
                # DenseGeneral geometry: the "o" projection contracts its
                # LEADING dims (heads, dim); every other projection
                # contracts the single leading input dim
                if path and path[-1] == "o":
                    k = int(np.prod(w.shape[:-1]))
                    w2d = w.reshape(k, w.shape[-1])
                else:
                    k = w.shape[0]
                    w2d = w.reshape(k, -1)
                if bits == 4:
                    from unionml_tpu.ops.int4_matmul import (
                        quantize_kernel_int4,
                        tile_for,
                    )

                    # column-parallel sites shard N: their tile must
                    # divide the per-device channel count (matches the
                    # shards= each Int4DenseGeneral site declares)
                    col_parallel = path and path[-1] in (
                        "q", "k", "v", "gate", "up"
                    )
                    shards = tensor if col_parallel else 1
                    tile = tile_for(w2d.shape[1], w2d.shape[0], shards=shards)
                    if tile and (
                        group_size == 0 or w2d.shape[0] % group_size == 0
                    ):
                        p, scale = quantize_kernel_int4(
                            w2d, tile, group_size=group_size
                        )
                        out = {
                            "kernel_p": p,
                            ("scale_g" if group_size else "scale"): scale,
                        }
                        for extra, v in tree.items():
                            if extra != "kernel":
                                out[extra] = v
                        return out
                    # odd output width / indivisible K-group: int8 below
                q, scale = _quantize_kernel_2d(w2d)
                out = {"kernel_q": q, "scale": scale}
                for extra, v in tree.items():
                    if extra != "kernel":
                        out[extra] = v
                return out
        if isinstance(tree, dict):
            return {k: walk(path + (k,), v) for k, v in tree.items()}
        return tree

    return walk((), params)
