"""Autoregressive generation: jitted prefill + ``lax.scan`` decode.

No reference counterpart — the reference's predictors are single
sklearn/torch calls (reference: unionml/model.py:498-499); LLM serving
(BASELINE.json config #5, "Llama-3-8B FastAPI predictor serving") needs a
generation loop, and on TPU that loop must live inside ONE compiled
program: Python-driven token-at-a-time decoding pays a dispatch round
trip per token (milliseconds through a tunneled backend — more than the
decode step itself).

Design:

- **prefill** runs the prompt through the model once, filling the KV
  cache (one big MXU-friendly matmul pass);
- **decode** is a ``lax.scan`` over ``max_new_tokens`` steps: each step
  feeds one token per sequence with ``cache_index`` advancing, so the
  whole generation is a single XLA program with static shapes —
  recompiles happen per (batch, prompt_len, max_new_tokens) bucket only;
- **sampling** is greedy at ``temperature=0`` else temperature softmax
  with optional top-k and/or nucleus top-p filters, driven by a threaded
  PRNG key;
- **eos** handling keeps shapes static: once a sequence emits
  ``eos_id`` every later token becomes ``pad_id`` and generation simply
  runs out the scan (correct, just not early-exiting — the standard
  static-shape trade).

Prompts in one call must share a length (serving buckets by prompt
length — see :mod:`unionml_tpu.serving.batcher`): the per-batch scalar
``cache_index`` is what keeps the decode step a cheap dynamic-slice.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from unionml_tpu.models.llama import Llama, LlamaConfig, init_cache
from unionml_tpu.models.train import resolve_params


def make_sampler(
    *,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> Callable:
    """Build ``sample(logits[B, V], key) -> token[B]``.

    Greedy at ``temperature == 0``; otherwise categorical over
    temperature-scaled logits, optionally filtered by ``top_k`` and/or
    nucleus ``top_p`` (keep the smallest prefix of probability-descending
    tokens whose mass reaches ``top_p``; the filters compose — top_k
    first, then top_p over the survivors). Shared by the scan generator
    and the continuous-batching decode engine so both sample identically.
    """
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")

    def sample(logits: jnp.ndarray, key) -> jnp.ndarray:
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits / temperature
        if top_k is not None:
            top_vals, _ = jax.lax.top_k(scaled, top_k)
            cutoff = top_vals[:, -1:]
            scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)
        if top_p is not None and top_p < 1.0:
            probs = jax.nn.softmax(scaled, axis=-1)
            sort_idx = jnp.argsort(probs, axis=-1)[:, ::-1]        # descending
            sorted_probs = jnp.take_along_axis(probs, sort_idx, axis=-1)
            cum = jnp.cumsum(sorted_probs, axis=-1)
            # keep the smallest prefix whose mass reaches top_p: a sorted
            # position survives iff the mass BEFORE it is < top_p. Masking
            # by position (not probability value) keeps the nucleus
            # bounded even when many tokens tie at the cutoff.
            keep_sorted = (cum - sorted_probs) < top_p
            inv = jnp.argsort(sort_idx, axis=-1)
            keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
            scaled = jnp.where(keep, scaled, -jnp.inf)
        return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)

    return sample


def make_generator(
    module: Llama,
    *,
    max_new_tokens: int,
    max_len: Optional[int] = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    eos_id: Optional[int] = None,
    pad_id: int = 0,
    prefill_chunk: Optional[int] = None,
    prefix_len: int = 0,
) -> Callable:
    """Build ``generate(params, tokens, key) -> tokens[B, max_new_tokens]``.

    ``tokens``: int32 [B, prompt_len] (equal lengths per call). The
    returned function is jit-compiled; XLA caches one executable per
    (batch, prompt_len) shape.

    Sampling: greedy at ``temperature == 0``; otherwise categorical over
    temperature-scaled logits, optionally filtered by ``top_k`` and/or
    nucleus ``top_p`` (keep the smallest prefix of
    probability-descending tokens whose mass reaches ``top_p``; the
    filters compose — top_k first, then top_p over the survivors).

    ``prefix_len > 0`` enables SHARED-PREFIX serving (system prompts):
    ``generate`` then takes a ``prefix_cache`` built once per weights by
    :func:`make_prefix_cache` holding the prefix's KV rows at
    ``[0, prefix_len)``; each request prefills only its own suffix, so
    the shared prefix's prefill cost is paid once per weights instead of
    once per request (~0.4 s per batch for a 512-token prefix at 8B).
    """
    cfg: LlamaConfig = module.config
    total_len = max_len or cfg.max_len
    sample = make_sampler(temperature=temperature, top_k=top_k, top_p=top_p)

    def generate(
        params, tokens: jnp.ndarray, key=None, prompt_mask=None,
        prefix_cache=None,
    ) -> jnp.ndarray:
        """``prompt_mask``: bool [B, prompt_len], False marks left-padding
        (padded slots are never attended to; RoPE positions are logical,
        i.e. counted over real tokens only)."""
        batch, prompt_len = tokens.shape
        if prefix_len + prompt_len + max_new_tokens > total_len:
            # dynamic_update_slice would clamp writes past the cache end
            # onto the last slot — silent corruption, so reject at trace
            raise ValueError(
                f"prefix_len {prefix_len} + prompt_len {prompt_len} + "
                f"max_new_tokens {max_new_tokens} exceeds the KV cache "
                f"length {total_len}; raise max_len"
            )
        if (prefix_cache is None) != (prefix_len == 0):
            raise ValueError(
                "prefix_cache must be passed exactly when the generator "
                f"was built with prefix_len > 0 (prefix_len={prefix_len})"
            )
        if key is None:
            if temperature != 0.0:
                # a silent fixed-key default would return byte-identical
                # "samples" on every call
                raise ValueError(
                    "temperature sampling needs an explicit PRNG key: "
                    "generate(params, tokens, key)"
                )
            key = jax.random.PRNGKey(0)  # greedy: key is never consumed
        if prompt_mask is None:
            prompt_mask = jnp.ones((batch, prompt_len), bool)
        pad_counts = prompt_len - prompt_mask.sum(axis=1).astype(jnp.int32)  # [B]
        # logical (RoPE) positions continue from the prefix's real tokens
        positions = prefix_len + jnp.maximum(
            jnp.arange(prompt_len, dtype=jnp.int32)[None, :] - pad_counts[:, None], 0
        )
        # padded prompt slots stay invisible forever; decode slots become
        # visible through the causal q_pos >= kv_pos rule as they fill;
        # prefix slots are always visible
        kv_mask = jnp.concatenate(
            [
                jnp.ones((batch, prefix_len), bool),
                prompt_mask,
                jnp.ones(
                    (batch, total_len - prefix_len - prompt_len), bool
                ),
            ],
            axis=1,
        )

        if prefix_cache is not None:
            # the prefix KV rows were prefilled ONCE (make_prefix_cache);
            # broadcast the [1, ...] buffers across this batch
            cache = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x, (batch,) + x.shape[1:]),
                prefix_cache,
            )
        else:
            cache = init_cache(cfg, batch, total_len)
        # prefill. The head runs on the LAST position only (prompts are
        # left-padded, so the last slot is the last real token): a
        # full-sequence head materializes [B, S, vocab] fp32 — 33 GB at
        # 8B x batch 8 x 8k. ``prefill_chunk`` additionally bounds the
        # cached-attention score buffer ([B, H, chunk, total] fp32
        # instead of [B, H, S, total]) — the knob that makes 8k-context
        # prefill fit at all (BASELINE.md round 3). The chunk loop is a
        # lax.scan (ONE compiled chunk body), not a Python unroll — 63
        # unrolled 8B chunk applies took the remote compiler >20 min.
        step_size = prefill_chunk or prompt_len
        n_chunks = max(0, (prompt_len - 1) // step_size)  # before the tail
        tail_start = n_chunks * step_size
        if n_chunks > 0:
            lead = tokens[:, :tail_start].reshape(batch, n_chunks, step_size)
            lead_pos = positions[:, :tail_start].reshape(
                batch, n_chunks, step_size
            )
            starts = prefix_len + jnp.arange(n_chunks, dtype=jnp.int32) * step_size

            def chunk_body(carry, xs):
                toks_c, pos_c, start = xs
                # logit_index=0: the head output is unused and DCE'd; the
                # chunk exists only to fill its cache rows
                _, carry = module.apply(
                    {"params": params}, toks_c, positions=pos_c,
                    cache=carry, cache_index=start, kv_mask=kv_mask,
                    logit_index=jnp.zeros((batch,), jnp.int32),
                )
                return carry, None

            cache, _ = jax.lax.scan(
                chunk_body, cache,
                (lead.transpose(1, 0, 2), lead_pos.transpose(1, 0, 2), starts),
            )
        tail_len = prompt_len - tail_start
        # static promise for cfg.prefill_impl == "flash": the tail call IS
        # the whole prefill exactly when nothing precedes it (no shared
        # prefix, no lead chunks) — both are Python ints at trace time.
        # The kwarg is only passed when the config opts in, so module
        # families without the parameter are untouched.
        full_kwargs = (
            {"full_prefill": True}
            if getattr(cfg, "prefill_impl", "cached") == "flash"
            and prefix_len + tail_start == 0
            else {}
        )
        logits, cache = module.apply(
            {"params": params}, tokens[:, tail_start:],
            positions=positions[:, tail_start:],
            cache=cache, cache_index=jnp.int32(prefix_len + tail_start),
            kv_mask=kv_mask,
            logit_index=jnp.full((batch,), tail_len - 1, jnp.int32),
            **full_kwargs,
        )
        key, sub = jax.random.split(key)
        first = sample(logits[:, -1], sub)
        done = (first == eos_id) if eos_id is not None else jnp.zeros(batch, bool)

        def step(carry, key_step):
            cache, tok, index, done = carry
            pos = (index - pad_counts)[:, None]   # logical positions [B, 1]
            logits, cache = module.apply(
                {"params": params}, tok[:, None], positions=pos,
                cache=cache, cache_index=index, kv_mask=kv_mask,
            )
            nxt = sample(logits[:, -1], key_step)
            if eos_id is not None:
                nxt = jnp.where(done, pad_id, nxt)
                done = done | (nxt == eos_id)
            return (cache, nxt, index + 1, done), nxt

        if max_new_tokens == 1:
            return first[:, None]
        keys = jax.random.split(key, max_new_tokens - 1)
        (_, _, _, _), rest = jax.lax.scan(
            step, (cache, first, jnp.int32(prefix_len + prompt_len), done), keys
        )
        return jnp.concatenate([first[:, None], rest.T], axis=1)

    jitted = jax.jit(generate)
    if prefix_len == 0:
        def plain(params, tokens, key=None, prompt_mask=None, prefix_cache=None):
            if prefix_cache is not None:
                # raise here, not inside jit: an unregistered PrefixCache
                # dataclass would die in pytree flattening with an opaque
                # "not a valid JAX type" error
                raise ValueError(
                    "prefix_cache must be passed exactly when the "
                    "generator was built with prefix_len > 0 "
                    "(prefix_len=0)"
                )
            return jitted(params, tokens, key, prompt_mask)

        return plain

    def prefixed(params, tokens, key=None, prompt_mask=None, prefix_cache=None):
        # validate the wrapper OUTSIDE the jit boundary (an unregistered
        # dataclass would die in pytree flattening with an opaque error):
        # a cache built for a different prefix or max_len would be
        # silently overwritten/misread otherwise
        if prefix_cache is None:
            raise ValueError(
                "prefix_cache must be passed exactly when the generator "
                f"was built with prefix_len > 0 (prefix_len={prefix_len})"
            )
        if not isinstance(prefix_cache, PrefixCache):
            raise TypeError(
                "prefix_cache must come from make_prefix_cache "
                f"(got {type(prefix_cache).__name__})"
            )
        if prefix_cache.length != prefix_len or prefix_cache.total_len != total_len:
            raise ValueError(
                f"prefix_cache was built for prefix_len={prefix_cache.length}, "
                f"max_len={prefix_cache.total_len}; this generator needs "
                f"prefix_len={prefix_len}, max_len={total_len}"
            )
        return jitted(params, tokens, key, prompt_mask, prefix_cache.cache)

    return prefixed


@dataclass(frozen=True)
class PrefixCache:
    """A prefilled shared-prefix KV cache plus the geometry it was built
    for — :func:`make_generator`'s prefixed form validates ``length`` /
    ``total_len`` against its own configuration, so a cache built for a
    different prefix or cache size is rejected instead of silently
    conditioning generation on the wrong rows."""

    cache: Any
    length: int
    total_len: int


def make_prefix_cache(
    module: Llama,
    params,
    prefix_tokens,
    *,
    max_len: Optional[int] = None,
    prefill_chunk: Optional[int] = None,
) -> PrefixCache:
    """Prefill a shared prefix (system prompt) ONCE into a [1, max_len]
    KV cache for :func:`make_generator`'s ``prefix_len`` mode.

    Returns a :class:`PrefixCache` whose pytree (bf16 or int8 per
    ``config.kv_quant``) has rows ``[0, len(prefix_tokens))`` filled;
    ``generate`` broadcasts it across each request batch and prefills
    only the per-request suffix. Rebuild whenever ``params`` change (the
    predictor's ``system_prefix`` mode memoizes per state identity).
    """
    cfg: LlamaConfig = module.config
    total_len = max_len or cfg.max_len
    toks = jnp.asarray(prefix_tokens, jnp.int32)[None]
    prefix_len = toks.shape[1]
    if prefix_len == 0:
        raise ValueError("prefix_tokens must be non-empty")
    if prefix_len >= total_len:
        raise ValueError(
            f"prefix of {prefix_len} tokens leaves no cache room within "
            f"max_len {total_len}"
        )

    def build(params, toks):
        cache = init_cache(cfg, 1, total_len)
        step_size = prefill_chunk or prefix_len
        n_chunks = max(0, (prefix_len - 1) // step_size)
        tail_start = n_chunks * step_size
        positions = jnp.arange(prefix_len, dtype=jnp.int32)[None, :]
        if n_chunks > 0:
            lead = toks[:, :tail_start].reshape(1, n_chunks, step_size)
            lead_pos = positions[:, :tail_start].reshape(1, n_chunks, step_size)
            starts = jnp.arange(n_chunks, dtype=jnp.int32) * step_size

            def chunk_body(carry, xs):
                toks_c, pos_c, start = xs
                _, carry = module.apply(
                    {"params": params}, toks_c, positions=pos_c,
                    cache=carry, cache_index=start,
                    logit_index=jnp.zeros((1,), jnp.int32),
                )
                return carry, None

            cache, _ = jax.lax.scan(
                chunk_body, cache,
                (lead.transpose(1, 0, 2), lead_pos.transpose(1, 0, 2), starts),
            )
        # same static full-prefill promise as generate()'s tail: when the
        # tail covers the whole (unpadded) prefix, cfg.prefill_impl ==
        # "flash" may run it through the flash kernel
        full_kwargs = (
            {"full_prefill": True}
            if getattr(cfg, "prefill_impl", "cached") == "flash"
            and tail_start == 0
            else {}
        )
        _, cache = module.apply(
            {"params": params}, toks[:, tail_start:],
            positions=positions[:, tail_start:],
            cache=cache, cache_index=jnp.int32(tail_start),
            logit_index=jnp.zeros((1,), jnp.int32),
            **full_kwargs,
        )
        return cache

    return PrefixCache(
        cache=jax.jit(build)(params, toks),
        length=prefix_len,
        total_len=total_len,
    )


def make_lm_predictor(
    module: Llama,
    *,
    max_new_tokens: int = 32,
    max_len: Optional[int] = None,
    bucket_lens: tuple = (16, 32, 64, 128, 256, 512),
    pad_id: int = 0,
    seed: int = 0,
    system_prefix=None,
    **gen_kwargs,
) -> Callable:
    """An ``@model.predictor``-compatible fn over token-id prompts.

    Accepts a list of token-id lists (or an int array); left-truncates/
    right-pads each prompt to the smallest bucket length so XLA sees a
    bounded set of shapes, generates, and returns one token list per
    prompt. Padding tokens are masked out of attention and RoPE positions
    are logical, so a padded prompt generates exactly what its unpadded
    version would.

    With ``temperature > 0`` the PRNG key advances per call (seeded by
    ``seed``), so repeated identical requests draw fresh samples; greedy
    decoding ignores the key.

    ``system_prefix`` (a token-id list): a shared prefix every request is
    conditioned on. Its KV rows are prefilled ONCE per weights
    (:func:`make_prefix_cache`, one cache per bucket, memoized on params
    identity) and broadcast into each request batch, so per-request
    prefill covers only the user prompt — outputs are exactly those of
    prepending the prefix to every prompt.

    **Identity contract**: the prefix memo keys on the STATE OBJECT —
    serving must hold one state object for the lifetime of the weights.
    A caller that re-wraps the same buffers per call (``device_put`` per
    request, a fresh dict from a checkpoint-reload loop) silently
    re-prefills the shared prefix every request, degrading the ~-42%
    p50 win back to naive; the predictor logs a warning when it detects
    a rebuild over leaves it has already seen.
    """
    import numpy as np

    prefix = (
        None
        if system_prefix is None
        else np.asarray(system_prefix, np.int32).ravel()
    )
    if prefix is not None and prefix.size == 0:
        # an empty array would thread prefix_len=0 into make_prefix_cache
        # and die in a ZeroDivisionError at the first request
        raise ValueError("system_prefix must be non-empty when given")
    prefix_len = 0 if prefix is None else len(prefix)
    total_len = max_len or module.config.max_len
    # only buckets that leave room for generation (and the prefix) in the
    # KV cache
    usable = tuple(sorted(
        b for b in bucket_lens
        if prefix_len + b + max_new_tokens <= total_len
    ))
    if not usable:
        raise ValueError(
            f"no bucket in {bucket_lens} leaves room for {max_new_tokens} new "
            f"tokens{f' + a {prefix_len}-token system_prefix' if prefix_len else ''} "
            f"within max_len {total_len}"
        )
    # one generator per bucket, each with a cache sized to the bucket:
    # decode attention reads the whole cache every step, so a full-length
    # (cfg.max_len) cache costs up to ~4x p50 at batch 8 on short prompts
    # (measured, 1.5B on v5e). XLA compiles per shape either way — the
    # per-bucket generators don't add executables.
    generators = {
        b: make_generator(
            module, max_new_tokens=max_new_tokens,
            max_len=prefix_len + b + max_new_tokens,
            pad_id=pad_id, prefix_len=prefix_len, **gen_kwargs,
        )
        for b in usable
    }
    key_state = {"key": jax.random.PRNGKey(seed)}
    # single-slot memo keyed on the STATE object (pre-resolution), with a
    # strong reference held: LoRA states resolve to a FRESH merged tree
    # every call (id(params) would miss forever and re-prefill per
    # request), and holding the referent prevents the
    # freed-then-id-reused hazard of a raw id() key. Serving holds one
    # weight set at a time; passing a new state object rebuilds.
    prefix_state = {"ref": None, "caches": {}}

    def _prefix_cache(state, params, bucket):
        if prefix is None:
            return None
        if prefix_state["ref"] is not state:
            # same underlying buffers under a new wrapper object → the
            # caller is violating the identity contract (see docstring):
            # every request now pays a full prefix prefill. Warn rather
            # than guess — keying on buffer ids would wrongly SHARE the
            # memo across genuinely different states that alias a leaf.
            leaves = jax.tree_util.tree_leaves(params)
            leaf_id = id(leaves[0]) if leaves else None
            if (
                prefix_state["ref"] is not None
                and leaf_id is not None
                and leaf_id == prefix_state.get("leaf_id")
            ):
                from unionml_tpu._logging import logger

                logger.info(
                    "system_prefix cache rebuilt for a state wrapping the "
                    "SAME weight buffers — hold one state object per "
                    "weight set or every request re-prefills the prefix"
                )
            prefix_state.update(ref=state, caches={}, leaf_id=leaf_id)
        caches = prefix_state["caches"]
        if bucket not in caches:
            caches[bucket] = make_prefix_cache(
                module, params, prefix,
                max_len=prefix_len + bucket + max_new_tokens,
                prefill_chunk=gen_kwargs.get("prefill_chunk"),
            )
        return caches[bucket]

    def predictor(state, prompts) -> list:
        params = resolve_params(state)
        if isinstance(prompts, (list, tuple)):
            rows = [np.asarray(p, dtype=np.int32).ravel() for p in prompts]
        else:
            arr = np.asarray(prompts, dtype=np.int32)
            rows = [arr] if arr.ndim == 1 else list(arr)
        longest = max(len(r) for r in rows)
        bucket = next((b for b in usable if b >= longest), usable[-1])
        # bucket the BATCH dimension too (next power of two): otherwise
        # every distinct batch size compiles a fresh executable
        n = len(rows)
        n_padded = 1 << (n - 1).bit_length()
        batch = np.full((n_padded, bucket), pad_id, np.int32)
        mask = np.zeros((n_padded, bucket), bool)
        for i in range(n_padded):
            r = rows[min(i, n - 1)]               # pad rows replicate the last
            r = r[-bucket:]                       # left-truncate long prompts
            batch[i, bucket - len(r):] = r        # right-align (left-pad)
            mask[i, bucket - len(r):] = True
        key_state["key"], sub = jax.random.split(key_state["key"])
        out = generators[bucket](
            params, jnp.asarray(batch), sub, jnp.asarray(mask),
            prefix_cache=_prefix_cache(state, params, bucket),
        )
        return np.asarray(out)[:n].tolist()

    def warmup(state, *, max_batch: int = 8, buckets: Optional[tuple] = None) -> int:
        """Pre-compile every (bucket, power-of-two batch) executable.

        XLA compiles lazily per shape; in a live server the first request
        hitting a fresh (bucket, padded-batch) combination stalls behind a
        multi-second compile (measured: 17.9 s p95 under 8 concurrent
        clients on the 1.5B config — vs ~0.4 s once warm). Call this at
        startup (pass it to ``ServingApp(warmup=...)``). Returns the
        number of executables compiled.
        """
        compiled = 0
        if buckets is not None:
            # a bucket outside `usable` (filtered out for leaving no KV-cache
            # room, or never configured) would silently warm the covering
            # bucket instead — callers would believe shapes were compiled
            # that weren't; an empty tuple would silently warm nothing
            if not buckets:
                raise ValueError(
                    "warmup got an empty bucket tuple — pass buckets=None "
                    "to warm every usable bucket"
                )
            unknown = sorted(set(buckets) - set(usable))
            if unknown:
                raise ValueError(
                    f"warmup buckets {unknown} are not in the usable bucket "
                    f"set {usable} (bucket_lens filtered to those leaving "
                    f"room for max_new_tokens={max_new_tokens} within "
                    f"max_len {total_len})"
                )
        # the predictor pads batches to the next power of two, so warm up
        # through max_batch ROUNDED UP — warmup(max_batch=6) must compile
        # batch 8, the shape a 5- or 6-row request actually runs
        top = 1 << (max(1, max_batch) - 1).bit_length()
        for b in usable if buckets is None else buckets:
            n = 1
            while n <= top:
                predictor(state, np.zeros((n, b), np.int32))
                compiled += 1
                n *= 2
        return compiled

    predictor.warmup = warmup
    return predictor


def serving_params(params, dtype=jnp.bfloat16):
    """Cast float params once for serving residency.

    Training artifacts carry fp32 master weights; decoding straight from
    them re-reads (and casts) the fp32 tree every step. A one-time cast
    to ``dtype`` halves decode weight traffic (~12% p50 on the 1.5B
    serving config, one v5e chip). Integer leaves (e.g. int8 ``kernel_q``)
    pass through unchanged, and so does quantization metadata that is
    fp32 *by contract*: per-channel ``scale`` / ``*_scale`` leaves (the
    dequant contract is "apply the fp32 scale, then one cast down") and
    the MoE ``router_kernel`` (kept fp32 so tiny routing updates don't
    round to zero) — so quantize-then-cast and cast-then-quantize agree.
    """

    from collections.abc import Mapping

    def cast_leaf(x):
        return x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x

    def walk(node):
        if isinstance(node, Mapping):
            out = {}
            for k, v in node.items():
                if isinstance(v, Mapping) or not hasattr(v, "dtype"):
                    out[k] = walk(v)
                    continue
                # a scale is quant metadata only next to its int8/int4
                # sibling (QuantizedDenseGeneral: kernel_q+scale;
                # Int4DenseGeneral: kernel_p+scale or group-wise
                # scale_g; MoE experts: w_*_q + w_*_scale) — norm params
                # also named "scale" cast
                is_quant_scale = (
                    k in ("scale", "scale_g")
                    and ("kernel_q" in node or "kernel_p" in node)
                ) or (
                    k.endswith("_scale") and f"{k[: -len('_scale')]}_q" in node
                )
                if k == "router_kernel" or is_quant_scale:
                    out[k] = v
                else:
                    out[k] = cast_leaf(v)
            return out
        if hasattr(node, "dtype"):
            return cast_leaf(node)
        return jax.tree_util.tree_map(cast_leaf, node)

    return walk(params)
