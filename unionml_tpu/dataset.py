"""Dataset: declarative data source, splitting, parsing, and feature pipeline.

Capability parity with reference unionml/dataset.py:35-510, redesigned
array-first for TPU: the canonical in-memory format is numpy/JAX arrays (a
pandas adapter is kept for tabular workflows, matching the reference's
pandas-first defaults). The reader compiles into a named, cacheable
:class:`~unionml_tpu.stage.Stage`; the loader→splitter→parser pipeline runs
host-side and feeds the device data path
(:mod:`unionml_tpu.data.pipeline`).

Registration points (all decoration-time type-checked, reference
dataset.py:95-205):

- ``reader`` (required): fetch raw data, annotated return type defines the
  dataset datatype.
- ``loader``: raw → loaded form (e.g. JSON str → DataFrame).
- ``splitter``: loaded → train/test splits.
- ``parser``: one split → model-ready tuple (features, targets).
- ``feature_loader``: raw serving input → loaded features.
- ``feature_transformer``: loaded features → model-ready features.
"""

from __future__ import annotations

import json
from dataclasses import field, make_dataclass
from enum import Enum
from inspect import Parameter

from unionml_tpu.type_guards import signature
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple, Union, get_args

import numpy as np

from unionml_tpu import type_guards
from unionml_tpu.stage import Stage, stage_from_fn
from unionml_tpu.tracking import TrackedInstance


class ReaderReturnTypeSource(Enum):
    """Which registered fn determines the dataset datatype (reference: dataset.py:30)."""

    READER = "reader"
    LOADER = "loader"


class Dataset(TrackedInstance):
    """Declarative dataset spec (reference: unionml/dataset.py:35)."""

    def __init__(
        self,
        name: str = "dataset",
        *,
        features: Optional[List[str]] = None,
        targets: Optional[List[str]] = None,
        test_size: float = 0.2,
        shuffle: bool = True,
        random_state: int = 12345,
    ):
        super().__init__()
        self.name = name
        self._features = features
        self._targets = targets or []
        self._test_size = test_size
        self._shuffle = shuffle
        self._random_state = random_state

        self._reader: Optional[Callable] = None
        self._reader_task_kwargs: Dict[str, Any] = {}
        self._loader: Callable = self._default_loader
        self._splitter: Callable = self._default_splitter
        self._parser: Callable = self._default_parser
        self._parser_feature_key: int = 0
        self._feature_loader: Callable = self._default_feature_loader
        self._feature_transformer: Callable = self._default_feature_transformer

        self._dataset_task: Optional[Stage] = None
        self._dataset_datatype: Optional[Dict[str, Any]] = None
        self._reader_input_types: Optional[List[Parameter]] = None
        self._loader_kwargs_type: Optional[type] = None
        self._splitter_kwargs_type: Optional[type] = None
        self._parser_kwargs_type: Optional[type] = None

    # ------------------------------------------------------------------ #
    # registration decorators (reference: dataset.py:95-205)
    # ------------------------------------------------------------------ #

    def reader(self, fn=None, **reader_task_kwargs):
        """Register the data reader; ``**reader_task_kwargs`` forward stage
        knobs like ``cache=True, cache_version="1"`` and ``resources=``
        (reference: dataset.py:95-108; caching used by the quickdraw
        template)."""
        if fn is None:
            return lambda f: self.reader(f, **reader_task_kwargs)
        type_guards.guard_reader(fn)
        self._reader = fn
        self._reader_task_kwargs = reader_task_kwargs
        self._dataset_task = None
        return fn

    def loader(self, fn):
        """Register raw-data loader (reference: dataset.py:110-123)."""
        type_guards.guard_loader(fn, self._reader_datatype())
        self._loader = fn
        self._loader_kwargs_type = None
        return fn

    def splitter(self, fn):
        """Register train/test splitter (reference: dataset.py:125-148)."""
        type_guards.guard_splitter(fn, self.dataset_datatype["data"], self.dataset_datatype_source.value)
        self._splitter = fn
        self._splitter_kwargs_type = None
        return fn

    def parser(self, fn=None, feature_key: int = 0):
        """Register split parser; ``feature_key`` indexes the features element
        in the parser output tuple (reference: dataset.py:150-174)."""
        if fn is None:
            return lambda f: self.parser(f, feature_key=feature_key)
        type_guards.guard_parser(fn, self.dataset_datatype["data"], self.dataset_datatype_source.value)
        self._parser = fn
        self._parser_feature_key = feature_key
        self._parser_kwargs_type = None
        return fn

    def feature_loader(self, fn):
        """Register raw-serving-input loader (reference: dataset.py:176-190)."""
        type_guards.guard_feature_loader(fn)
        self._feature_loader = fn
        return fn

    def feature_transformer(self, fn):
        """Register features transformer (reference: dataset.py:192-205)."""
        type_guards.guard_feature_transformer(fn)
        self._feature_transformer = fn
        return fn

    # ------------------------------------------------------------------ #
    # canonical kwargs + dynamic dataclass synthesis
    # (reference: dataset.py:207-272)
    # ------------------------------------------------------------------ #

    @property
    def splitter_kwargs(self) -> Dict[str, Any]:
        """Canonical kwargs always forwarded to the splitter
        (reference: dataset.py:207-214)."""
        return {
            "test_size": self._test_size,
            "shuffle": self._shuffle,
            "random_state": self._random_state,
        }

    @property
    def parser_kwargs(self) -> Dict[str, Any]:
        """Canonical kwargs always forwarded to the parser
        (reference: dataset.py:216-222)."""
        return {"features": self._features, "targets": self._targets}

    @staticmethod
    def _fn_default_kwargs(fn: Callable) -> Dict[str, Any]:
        """Keyword defaults of ``fn`` past its first (data) argument."""
        out: Dict[str, Any] = {}
        for i, (k, p) in enumerate(signature(fn).parameters.items()):
            if i == 0 or p.kind in (Parameter.VAR_KEYWORD, Parameter.VAR_POSITIONAL):
                continue
            if p.default is not Parameter.empty:
                out[k] = p.default
        return out

    def _make_kwargs_type(self, type_name: str, fn: Callable, defaults: Dict[str, Any]) -> type:
        """Synthesize a dataclass from ``fn``'s post-data keyword interface
        (reference: dataset.py:224-272)."""
        fields = []
        for i, (k, p) in enumerate(signature(fn).parameters.items()):
            if i == 0 or p.kind in (Parameter.VAR_KEYWORD, Parameter.VAR_POSITIONAL):
                continue
            annotation = p.annotation if p.annotation is not Parameter.empty else Any
            if k in defaults:
                default = defaults[k]
            elif p.default is not Parameter.empty:
                default = p.default
            else:
                fields.append((k, annotation))
                continue
            # mutable defaults need default_factory (reference: dataset.py:224-231)
            if isinstance(default, (list, dict, set)):
                fields.append(
                    (k, annotation, field(default_factory=lambda d=default: d))
                )
            else:
                fields.append((k, annotation, default))
        return make_dataclass(type_name, fields)

    @property
    def loader_kwargs_type(self) -> type:
        if self._loader_kwargs_type is None:
            self._loader_kwargs_type = self._make_kwargs_type(
                "LoaderKwargs", self._loader, self._fn_default_kwargs(self._loader)
            )
        return self._loader_kwargs_type

    @property
    def splitter_kwargs_type(self) -> type:
        if self._splitter_kwargs_type is None:
            self._splitter_kwargs_type = self._make_kwargs_type(
                "SplitterKwargs", self._splitter, self.splitter_kwargs
            )
        return self._splitter_kwargs_type

    @property
    def parser_kwargs_type(self) -> type:
        if self._parser_kwargs_type is None:
            self._parser_kwargs_type = self._make_kwargs_type(
                "ParserKwargs", self._parser, self.parser_kwargs
            )
        return self._parser_kwargs_type

    # ------------------------------------------------------------------ #
    # compilation + execution (reference: dataset.py:274-345)
    # ------------------------------------------------------------------ #

    def dataset_task(self) -> Stage:
        """Compile the reader into a named stage (reference: dataset.py:274-292)."""
        if self._dataset_task is not None:
            return self._dataset_task
        if self._reader is None:
            raise ValueError(
                f"Dataset {self.name!r} has no reader. Register one with @dataset.reader."
            )
        reader = self._reader
        reader_sig = signature(reader)

        def dataset_task(**kwargs):
            return reader(**kwargs)

        self._dataset_task = stage_from_fn(
            dataset_task,
            owner=self,
            name=f"{self.name}.reader",
            parameters=list(reader_sig.parameters.values()),
            return_annotation=reader_sig.return_annotation,
            stage_method="dataset_task",
            **self._reader_task_kwargs,
        )
        return self._dataset_task

    def get_data(
        self,
        raw_data,
        loader_kwargs: Optional[Dict[str, Any]] = None,
        splitter_kwargs: Optional[Dict[str, Any]] = None,
        parser_kwargs: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """raw → loaded → split → parsed, keyed ``{"train": ..., "test": ...}``
        (reference: dataset.py:294-334)."""
        loader_kwargs = {**(loader_kwargs or {})}
        splitter_kwargs = {**self.splitter_kwargs, **(splitter_kwargs or {})}
        parser_kwargs = {**self.parser_kwargs, **(parser_kwargs or {})}

        data = self._loader(raw_data, **loader_kwargs)
        splits = self._splitter(data, **splitter_kwargs)
        if len(splits) == 1:
            return {"train": self._parser(splits[0], **parser_kwargs)}
        train_split, test_split = splits
        return {
            "train": self._parser(train_split, **parser_kwargs),
            "test": self._parser(test_split, **parser_kwargs),
        }

    def get_features(self, features) -> Any:
        """raw serving input → model-ready features (reference: dataset.py:336-345)."""
        return self._feature_transformer(self._feature_loader(features))

    # ------------------------------------------------------------------ #
    # type introspection (reference: dataset.py:348-408)
    # ------------------------------------------------------------------ #

    def _reader_datatype(self) -> Any:
        if self._reader is not None:
            return signature(self._reader).return_annotation
        if self._dataset_datatype is not None:
            return self._dataset_datatype["data"]
        return Any

    @property
    def reader_input_types(self) -> Optional[List[Parameter]]:
        if self._reader is not None and self._reader_input_types is None:
            return list(signature(self._reader).parameters.values())
        return self._reader_input_types

    @property
    def dataset_datatype(self) -> Dict[str, Any]:
        """Loader return type takes precedence over reader's
        (reference: dataset.py:355-369)."""
        if self._loader != self._default_loader:
            return {"data": signature(self._loader).return_annotation}
        if self._reader is not None:
            return {"data": signature(self._reader).return_annotation}
        if self._dataset_datatype is not None:
            return self._dataset_datatype
        raise ValueError(
            "dataset_datatype is not defined. Define a @dataset.reader with a "
            "return annotation."
        )

    @property
    def dataset_datatype_source(self) -> ReaderReturnTypeSource:
        if self._loader != self._default_loader:
            return ReaderReturnTypeSource.LOADER
        return ReaderReturnTypeSource.READER

    @property
    def parser_return_types(self) -> Tuple[Any, ...]:
        return get_args(signature(self._parser).return_annotation)

    @property
    def feature_type(self) -> Any:
        """Feature type for predictors (reference: dataset.py:385-408)."""
        parser_type = (
            self.dataset_datatype["data"]
            if self._parser == self._default_parser
            else (
                self.parser_return_types[self._parser_feature_key]
                if self.parser_return_types
                else Any
            )
        )
        if self._feature_transformer == self._default_feature_transformer:
            ft_type = signature(self._feature_loader).return_annotation
        else:
            ft_type = signature(self._feature_transformer).return_annotation
        if ft_type is Parameter.empty or ft_type is Any:
            return parser_type
        if parser_type != ft_type and parser_type not in (Parameter.empty, Any):
            return Union[ft_type, parser_type]
        return ft_type

    # ------------------------------------------------------------------ #
    # SQL data sources (reference: dataset.py:426-453)
    # ------------------------------------------------------------------ #

    @classmethod
    def from_sqlite_task(
        cls,
        name: str,
        *,
        db_path: str,
        query_template: str,
        **dataset_kwargs,
    ) -> "Dataset":
        """Dataset whose reader executes a SQLite query → DataFrame
        (reference: dataset.py:426-439). The query template is formatted
        with the reader kwargs, which become workflow inputs."""
        import pandas as pd

        dataset = cls(name, **dataset_kwargs)

        def reader(**query_kwargs) -> pd.DataFrame:
            import sqlite3

            # sqlite3's context manager only scopes transactions, not the
            # connection — close explicitly to avoid fd leaks in serving
            conn = sqlite3.connect(db_path)
            try:
                return pd.read_sql_query(query_template.format(**query_kwargs), conn)
            finally:
                conn.close()

        # surface the template's format fields as reader inputs
        import string

        field_names = [f for _, f, _, _ in string.Formatter().parse(query_template) if f]
        params = [Parameter(f, Parameter.KEYWORD_ONLY, annotation=Any) for f in field_names]
        reader.__signature__ = signature(reader).replace(
            parameters=params, return_annotation=pd.DataFrame
        )
        reader.__annotations__ = {f: Any for f in field_names}
        reader.__annotations__["return"] = pd.DataFrame
        dataset.reader(reader)
        return dataset

    @classmethod
    def from_sqlalchemy_task(
        cls,
        name: str,
        *,
        uri: str,
        query_template: str,
        **dataset_kwargs,
    ) -> "Dataset":
        """Dataset whose reader executes a SQLAlchemy query → DataFrame
        (reference: dataset.py:441-453)."""
        import pandas as pd

        dataset = cls(name, **dataset_kwargs)

        def reader(**query_kwargs) -> pd.DataFrame:
            import sqlalchemy  # gated: optional dependency

            engine = sqlalchemy.create_engine(uri)
            with engine.connect() as conn:
                return pd.read_sql_query(query_template.format(**query_kwargs), conn)

        import string

        field_names = [f for _, f, _, _ in string.Formatter().parse(query_template) if f]
        params = [Parameter(f, Parameter.KEYWORD_ONLY, annotation=Any) for f in field_names]
        reader.__signature__ = signature(reader).replace(
            parameters=params, return_annotation=pd.DataFrame
        )
        reader.__annotations__ = {f: Any for f in field_names}
        reader.__annotations__["return"] = pd.DataFrame
        dataset.reader(reader)
        return dataset

    # ------------------------------------------------------------------ #
    # array-first defaults (reference pandas defaults: dataset.py:455-510)
    # ------------------------------------------------------------------ #

    def _default_loader(self, data):
        """Identity: reader output is already the loaded form
        (reference: dataset.py:455-459)."""
        return data

    @staticmethod
    def _is_xy_pair(data) -> bool:
        """True for an ``(X, y)`` tuple of equal-length array-likes (the
        array-first reader contract) — vs. a plain 2-element sequence."""
        return (
            isinstance(data, (tuple, list))
            and len(data) == 2
            and any(hasattr(el, "shape") or hasattr(el, "iloc") for el in data)
            and all(hasattr(el, "__len__") for el in data)
            and len(data[0]) == len(data[1])
        )

    def _default_splitter(self, data, test_size: float, shuffle: bool, random_state: int):
        """Split DataFrames, arrays, (X, y) pairs, or sequences into
        (train, test) (reference sklearn-based splitter: dataset.py:461-470;
        rewritten with a numpy RNG so the core has no sklearn dependency)."""

        def take(d, idx):
            if hasattr(d, "iloc"):  # pandas
                return d.iloc[idx]
            if hasattr(d, "shape"):  # numpy/jax array
                return d[idx]
            return [d[int(i)] for i in idx]

        xy_pair = self._is_xy_pair(data)
        n = len(data[0]) if xy_pair else len(data)
        indices = np.arange(n)
        if shuffle:
            rng = np.random.default_rng(random_state)
            rng.shuffle(indices)
        n_test = int(np.floor(n * test_size))
        test_idx, train_idx = indices[:n_test], indices[n_test:]

        if xy_pair:  # split X and y along rows with shared indices
            return (
                tuple(take(el, train_idx) for el in data),
                tuple(take(el, test_idx) for el in data),
            )
        return take(data, train_idx), take(data, test_idx)

    def _default_parser(self, data, features: Optional[List[str]], targets: List[str]):
        """Split one data split into (features, targets)
        (reference: dataset.py:472-487).

        - pandas DataFrame: select feature/target columns.
        - dict of arrays: ``features``/``targets`` name keys.
        - tuple/list of two arrays: passthrough ``(X, y)``.
        """
        if hasattr(data, "loc"):  # pandas DataFrame
            if not features:
                features = [c for c in data.columns if c not in targets]
            try:
                target_frame = data[targets]
            except KeyError:
                target_frame = data.head(0)[[]]  # serving features: no targets
            return [data[features], target_frame]
        if isinstance(data, dict):
            feat = data["features"] if "features" in data else data[(features or ["x"])[0]]
            targ = data.get("targets")
            if targ is None and targets:
                targ = data.get(targets[0])
            return [feat, targ]
        if isinstance(data, (tuple, list)) and len(data) == 2:
            return [data[0], data[1]]
        return [data, None]

    def _default_feature_loader(self, features):
        """Accept a file path / JSON string / dict / list / array and return
        loaded features (reference: dataset.py:489-503). pandas is imported
        only on the tabular branches so array-first apps run pandas-free."""
        if isinstance(features, (str, Path)) and Path(str(features)).exists():
            with open(features) as f:
                features = json.load(f)
        elif isinstance(features, (str, bytes)):
            features = json.loads(features)
        if isinstance(features, np.ndarray):
            return features
        if hasattr(features, "loc"):  # already a DataFrame
            return features
        if isinstance(features, dict):
            import pandas as pd

            return pd.DataFrame(features)
        if isinstance(features, list) and features and isinstance(features[0], dict):
            import pandas as pd

            return pd.DataFrame.from_records(features)
        return np.asarray(features)

    def _default_feature_transformer(self, features):
        """Identity, after aligning DataFrame columns to the declared feature
        list (reference: dataset.py:505-510)."""
        if hasattr(features, "loc") and self._features:
            cols = [c for c in self._features if c in features.columns]
            if cols:
                return features[cols]
        return features
