"""Decoration-time signature contracts for user functions.

Capability parity with reference unionml/type_guards.py:79-191: every
registered function is checked at decoration time so that spec errors
surface immediately with a helpful message, not at execution time.

Differences from the reference are deliberate and TPU-motivated:
- ``typing.Any`` and missing dataset types are tolerated permissively (a
  JAX pytree has no single static type), but *declared* annotations must
  agree.
- JAX array / pytree types are treated as compatible with numpy array
  annotations, since host staging converts between them.
"""

from __future__ import annotations

import inspect
import typing
from inspect import Parameter
from typing import Any, Callable, Dict, Iterable, Mapping


def signature(fn: Callable) -> inspect.Signature:
    """``inspect.signature`` resolving PEP 563 string annotations.

    User app modules often use ``from __future__ import annotations``;
    guards must compare real types, not their string forms. Falls back to
    unresolved strings when a name can't be evaluated (the permissive
    ``_is_compatible`` then treats only exact matches as compatible).
    """
    try:
        return inspect.signature(fn, eval_str=True)
    except (NameError, TypeError, ValueError):
        return inspect.signature(fn)

# canonical keyword interfaces (reference: type_guards.py:12-22)
SPLITTER_KWARGS = {"test_size": float, "shuffle": bool, "random_state": int}
PARSER_KWARGS = {"features": typing.Optional[typing.List[str]], "targets": typing.List[str]}


class SignatureError(TypeError):
    """Raised when a registered function's signature violates its contract."""


def _type_name(t: Any) -> str:
    return getattr(t, "__name__", str(t))


def _is_compatible(actual: Any, expected: Any) -> bool:
    """Union-aware type compatibility (reference: type_guards.py:28-40).

    ``actual`` is compatible with ``expected`` when they are equal, either
    side is ``Any``/unannotated, or when one is a Union whose args contain
    (or are contained by) the other side's args.
    """
    if expected is None or actual is None:
        return True
    if actual is Any or expected is Any:
        return True
    if actual is Parameter.empty or expected is Parameter.empty:
        return True
    if actual == expected:
        return True

    actual_args = set(typing.get_args(actual)) if _is_union(actual) else {actual}
    expected_args = set(typing.get_args(expected)) if _is_union(expected) else {expected}
    if actual_args & expected_args:
        return True
    # generic aliases: compare origins (List[float] vs list, etc.)
    a_origin = {typing.get_origin(t) or t for t in actual_args}
    e_origin = {typing.get_origin(t) or t for t in expected_args}
    return bool(a_origin & e_origin) and _args_overlap(actual, expected)


def _is_union(t: Any) -> bool:
    origin = typing.get_origin(t)
    if origin is typing.Union:
        return True
    # PEP 604 `X | Y`
    return type(t).__name__ == "UnionType"


def _args_overlap(actual: Any, expected: Any) -> bool:
    a_args, e_args = typing.get_args(actual), typing.get_args(expected)
    if not a_args or not e_args:
        return True
    return all(_is_compatible(a, e) for a, e in zip(a_args, e_args))


def _positional_params(fn: Callable) -> Dict[str, Parameter]:
    return {
        k: p
        for k, p in signature(fn).parameters.items()
        if p.kind in (Parameter.POSITIONAL_ONLY, Parameter.POSITIONAL_OR_KEYWORD)
    }


def _check_kwargs_accepted(fn_name: str, fn: Callable, kwtypes: Mapping[str, Any]) -> None:
    """Check that ``fn`` accepts the canonical keyword interface.

    Reference: type_guards.py:60-70. Functions may accept ``**kwargs`` to
    satisfy the contract wholesale.
    """
    params = signature(fn).parameters
    if any(p.kind is Parameter.VAR_KEYWORD for p in params.values()):
        return
    for key in kwtypes:
        if key not in params:
            raise SignatureError(
                f"'{fn_name}' must accept a '{key}' keyword argument "
                f"(canonical interface: {sorted(kwtypes)})."
            )


def guard_reader(reader: Callable) -> None:
    """Reader must declare a return annotation (reference: type_guards.py:79-85)."""
    ret = signature(reader).return_annotation
    if ret is inspect.Signature.empty:
        raise SignatureError(
            "The 'reader' function must have a return type annotation — it "
            "defines the dataset datatype for every downstream function."
        )


def guard_loader(loader: Callable, expected_data_type: Any) -> None:
    """Loader first arg must match the dataset datatype (reference: type_guards.py:88-92)."""
    params = _positional_params(loader)
    if not params:
        raise SignatureError("'loader' must take the raw dataset as its first argument.")
    first = next(iter(params.values()))
    if not _is_compatible(first.annotation, expected_data_type):
        raise SignatureError(
            f"'loader' first argument must be of type {_type_name(expected_data_type)}, "
            f"found {_type_name(first.annotation)}."
        )


def guard_splitter(splitter: Callable, expected_data_type: Any, source: str) -> None:
    """Splitter contract (reference: type_guards.py:95-104)."""
    params = _positional_params(splitter)
    if not params:
        raise SignatureError("'splitter' must take the loaded dataset as its first argument.")
    first = next(iter(params.values()))
    if not _is_compatible(first.annotation, expected_data_type):
        raise SignatureError(
            f"'splitter' first argument must match the {source} return type "
            f"{_type_name(expected_data_type)}, found {_type_name(first.annotation)}."
        )
    _check_kwargs_accepted("splitter", splitter, SPLITTER_KWARGS)


def guard_parser(parser: Callable, expected_data_type: Any, source: str) -> None:
    """Parser contract (reference: type_guards.py:107-115)."""
    params = _positional_params(parser)
    if not params:
        raise SignatureError("'parser' must take one data split as its first argument.")
    first = next(iter(params.values()))
    if not _is_compatible(first.annotation, expected_data_type):
        raise SignatureError(
            f"'parser' first argument must match the {source} return type "
            f"{_type_name(expected_data_type)}, found {_type_name(first.annotation)}."
        )
    _check_kwargs_accepted("parser", parser, PARSER_KWARGS)


def guard_trainer(
    trainer: Callable, expected_model_type: Any, expected_data_types: Iterable[Any]
) -> None:
    """Trainer contract (reference: type_guards.py:118-132).

    First argument and return type must be the model type; subsequent
    positional args must match the parsed-data types.
    """
    sig = signature(trainer)
    params = list(_positional_params(trainer).values())
    if not params:
        raise SignatureError("'trainer' must take the model object as its first argument.")
    if not _is_compatible(params[0].annotation, expected_model_type):
        raise SignatureError(
            f"'trainer' first argument must be the model type "
            f"{_type_name(expected_model_type)}, found {_type_name(params[0].annotation)}."
        )
    if not _is_compatible(sig.return_annotation, expected_model_type):
        raise SignatureError(
            f"'trainer' must return the model type {_type_name(expected_model_type)}, "
            f"found {_type_name(sig.return_annotation)}."
        )
    data_params = params[1:]
    expected = list(expected_data_types)
    if expected and data_params and len(data_params) > len(expected):
        raise SignatureError(
            f"'trainer' takes {len(data_params)} data arguments but the parser "
            f"produces {len(expected)} outputs."
        )
    for p, t in zip(data_params, expected):
        if not _is_compatible(p.annotation, t):
            raise SignatureError(
                f"'trainer' data argument '{p.name}' must be of type {_type_name(t)}, "
                f"found {_type_name(p.annotation)}."
            )


def guard_train_step(step: Callable) -> None:
    """A jittable step must accept exactly (state, batch) positionally.

    No reference counterpart (train_step is the TPU-native tier); same
    decoration-time contract philosophy as the reference's guards —
    misregistered steps fail at registration with a named error, not at
    first jit trace.
    """
    sig = signature(step)
    all_params = list(sig.parameters.values())
    params = [
        p
        for p in all_params
        if p.kind in (Parameter.POSITIONAL_ONLY, Parameter.POSITIONAL_OR_KEYWORD)
    ]
    has_var_pos = any(p.kind is Parameter.VAR_POSITIONAL for p in all_params)
    required = [p for p in params if p.default is Parameter.empty]
    required_kw_only = [
        p
        for p in all_params
        if p.kind is Parameter.KEYWORD_ONLY and p.default is Parameter.empty
    ]
    if (
        len(required) > 2
        or (len(params) < 2 and not has_var_pos)
        or required_kw_only
    ):
        raise SignatureError(
            f"'train_step' must be callable as step(state, batch) -> "
            f"(state, metrics); got signature {sig}."
        )


def guard_evaluator(
    evaluator: Callable, expected_model_type: Any, expected_data_types: Iterable[Any]
) -> None:
    """Evaluator contract (reference: type_guards.py:135-148)."""
    params = list(_positional_params(evaluator).values())
    if not params:
        raise SignatureError("'evaluator' must take the model object as its first argument.")
    if not _is_compatible(params[0].annotation, expected_model_type):
        raise SignatureError(
            f"'evaluator' first argument must be the model type "
            f"{_type_name(expected_model_type)}, found {_type_name(params[0].annotation)}."
        )
    for p, t in zip(params[1:], list(expected_data_types)):
        if not _is_compatible(p.annotation, t):
            raise SignatureError(
                f"'evaluator' data argument '{p.name}' must be of type {_type_name(t)}, "
                f"found {_type_name(p.annotation)}."
            )


def guard_predictor(predictor: Callable, expected_model_type: Any, expected_data_type: Any) -> None:
    """Predictor contract (reference: type_guards.py:151-169).

    Takes the model object plus exactly one features argument, and must
    declare a return annotation.
    """
    sig = signature(predictor)
    params = list(_positional_params(predictor).values())
    if not params:
        raise SignatureError("'predictor' must take the model object as its first argument.")
    if not _is_compatible(params[0].annotation, expected_model_type):
        raise SignatureError(
            f"'predictor' first argument must be the model type "
            f"{_type_name(expected_model_type)}, found {_type_name(params[0].annotation)}."
        )
    feature_params = params[1:]
    if len(feature_params) != 1:
        raise SignatureError(
            f"'predictor' must take exactly one features argument after the model "
            f"object, found {len(feature_params)}."
        )
    if not _is_compatible(feature_params[0].annotation, expected_data_type):
        raise SignatureError(
            f"'predictor' features argument must be of type "
            f"{_type_name(expected_data_type)}, found "
            f"{_type_name(feature_params[0].annotation)}."
        )
    if sig.return_annotation is inspect.Signature.empty:
        raise SignatureError("'predictor' must have a return type annotation.")


def guard_feature_loader(feature_loader: Callable) -> None:
    """Feature loader takes a single argument (reference: type_guards.py:172-181)."""
    params = list(_positional_params(feature_loader).values())
    if len(params) != 1:
        raise SignatureError(
            f"'feature_loader' must take exactly one argument (the raw features), "
            f"found {len(params)}."
        )


def guard_feature_transformer(feature_transformer: Callable) -> None:
    """Feature transformer takes a single argument (reference: type_guards.py:184-191)."""
    params = list(_positional_params(feature_transformer).values())
    if len(params) != 1:
        raise SignatureError(
            f"'feature_transformer' must take exactly one argument (loaded features), "
            f"found {len(params)}."
        )
