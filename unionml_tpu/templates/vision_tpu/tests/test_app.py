"""Scaffolded smoke test: cached reader + ViT train_step + file-loader
prediction path."""

import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent.parent))

import app


def test_train_and_predict_array_and_file(tmp_path):
    state, metrics = app.model.train(
        hyperparameters={"learning_rate": 1e-3},
        trainer_kwargs={"num_epochs": 1, "batch_size": 64},
        n=256,
    )
    assert "test" in metrics
    image = np.zeros((app.IMAGE_SIZE, app.IMAGE_SIZE, 3), np.float32)
    preds = app.model.predict(features=image[None])
    assert np.asarray(preds).shape == (1,)
    npy = tmp_path / "img.npy"
    np.save(npy, image)
    preds2 = app.model.predict(features=str(npy))
    assert np.asarray(preds2).shape == (1,)
