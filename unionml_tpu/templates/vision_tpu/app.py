"""{{app_name}}: a TPU-native computer-vision app (ViT on image batches).

Template parity: reference templates/quickdraw (PyTorch CV app with a
custom splitter, a custom feature_loader, and task caching —
reference: templates/quickdraw/{{cookiecutter.app_name}}/app.py:18,32,62).
TPU-native differences: the model is the framework's flax ViT, training
is a jittable ``train_step`` over a data-parallel mesh, the expensive
reader is cached with the stage cache (``cache=True, cache_version``),
and prediction accepts image files through a custom ``feature_loader``.

Run: ``python app.py`` (train + save), then
``unionml-tpu serve app:model --model-path model.utpu --batch``.
"""

from pathlib import Path
from typing import Union

import jax.numpy as jnp
import numpy as np
from unionml_tpu import Dataset, Model
from unionml_tpu.models import ViT, ViTConfig, classification_step, create_train_state
from unionml_tpu.parallel import ShardingConfig

IMAGE_SIZE = 32
NUM_CLASSES = 10

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.2)
model = Model(name="{{app_name}}", dataset=dataset)

module = ViT(ViTConfig.tiny(image_size=IMAGE_SIZE, num_classes=NUM_CLASSES))


# the reader is the expensive stage (decode/resize a whole corpus), so it
# is cached on disk: re-runs with the same kwargs hit the stage cache
# (reference caching knob: quickdraw app.py:18 `cache=True, cache_version="1"`)
@dataset.reader(cache=True, cache_version="1")
def reader(n: int = 512, seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)
    images = rng.normal(size=(n, IMAGE_SIZE, IMAGE_SIZE, 3)).astype(np.float32)
    # synthetic labels with learnable signal (channel-mean threshold)
    targets = (images.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    return {"features": images, "targets": targets}


# custom splitter: stratified-ish split keeping class balance
# (reference custom splitter: quickdraw app.py:24-30)
@dataset.splitter
def splitter(data: dict, test_size: float, shuffle: bool, random_state: int):
    n = len(data["features"])
    idx = np.arange(n)
    if shuffle:
        np.random.default_rng(random_state).shuffle(idx)
    k = int(n * (1 - test_size))
    tr, te = idx[:k], idx[k:]
    return (
        {"features": data["features"][tr], "targets": data["targets"][tr]},
        {"features": data["features"][te], "targets": data["targets"][te]},
    )


@dataset.parser
def parser(data: dict, features, targets):
    return (data["features"], data["targets"])


# custom feature loader: accept a path to an .npy image file, a list of
# nested lists, or a ready array (reference custom feature_loader:
# quickdraw app.py:44-55 decodes uploaded drawings)
@dataset.feature_loader
def feature_loader(raw: Union[str, Path, list, np.ndarray]) -> np.ndarray:
    if isinstance(raw, (str, Path)):
        arr = np.load(raw)
    else:
        arr = np.asarray(raw, dtype=np.float32)
    if arr.ndim == 3:  # single image -> batch of one
        arr = arr[None]
    return arr.astype(np.float32)


@model.init
def init(hyperparameters: dict) -> object:
    return create_train_state(
        module,
        jnp.zeros((1, IMAGE_SIZE, IMAGE_SIZE, 3)),
        learning_rate=hyperparameters.get("learning_rate", 1e-3),
        weight_decay=hyperparameters.get("weight_decay", 1e-4),
    )


@model.train_step(sharding=ShardingConfig(data=-1))
def train_step(state, batch) -> tuple:
    return classification_step(module)(state, batch)


@model.predictor(jit=True)
def predictor(state, features: np.ndarray) -> jnp.ndarray:
    logits = state.apply_fn({"params": state.params}, jnp.asarray(features))
    return jnp.argmax(logits, axis=-1)


@model.evaluator
def evaluator(state, features: np.ndarray, targets: np.ndarray) -> float:
    preds = predictor(state, features)
    return float((np.asarray(preds) == np.asarray(targets)).mean())


if __name__ == "__main__":
    state, metrics = model.train(
        hyperparameters={"learning_rate": 1e-3},
        trainer_kwargs={"num_epochs": 5, "batch_size": 64},
    )
    print(f"metrics: {metrics}")
    model.save("model.utpu")
