"""Scaffolded smoke test: both serverless handlers answer their events."""

import json
import sys
from pathlib import Path

ROOT = Path(__file__).parent.parent
sys.path.insert(0, str(ROOT))

import app


def test_gateway_and_upload_handlers(tmp_path):
    app.model.train(hyperparameters={"max_iter": 200})
    health = app.handler({"httpMethod": "GET", "path": "/health"})
    assert health["statusCode"] == 200

    event = json.loads((ROOT / "events" / "gateway_predict.json").read_text())
    resp = app.handler(event)
    assert resp["statusCode"] == 200
    assert json.loads(resp["body"])

    # object-store upload event (fixture: events/object_upload.json)
    store = app.LocalObjectStore(str(tmp_path))
    frame = app.reader().drop(columns=["target"]).head(2)
    store.put("uploads", "batch-001.json",
              json.dumps(frame.to_dict(orient="records")).encode())
    on_upload = app.object_event_handler(app.model, store)
    upload_event = json.loads((ROOT / "events" / "object_upload.json").read_text())
    out = on_upload(upload_event)
    assert out["statusCode"] == 200
    written = json.loads(store.get("uploads", "batch-001.json.predictions.json"))
    assert len(written) == 2
