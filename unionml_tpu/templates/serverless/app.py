"""{{app_name}}: a unionml-tpu app served as serverless event handlers.

Template parity: reference templates/basic-aws-lambda (API-Gateway
events via Mangum) and basic-aws-lambda-s3 (S3-event batch prediction).
Here both handlers come from :mod:`unionml_tpu.serving.serverless` and
need no Mangum/boto3: ``handler`` answers gateway events, ``on_upload``
reacts to object-store upload events (swap ``LocalObjectStore`` for a
cloud-backed store in production).

Try locally:
    python app.py                # train + save model.joblib
    UNIONML_MODEL_PATH=model.joblib python -c \
        "from app import handler; print(handler({'httpMethod': 'GET', 'path': '/health'}))"
"""

import pandas as pd
from sklearn.linear_model import LogisticRegression

from unionml_tpu import Dataset, Model
from unionml_tpu.serving.serverless import (
    LocalObjectStore,
    gateway_handler,
    object_event_handler,
)

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.2, shuffle=True, targets=["target"])
model = Model(name="{{app_name}}", init=LogisticRegression, dataset=dataset)


@dataset.reader
def reader(sample_frac: float = 1.0, random_state: int = 42) -> pd.DataFrame:
    from sklearn.datasets import load_digits

    frame = load_digits(as_frame=True).frame
    if sample_frac >= 1.0:
        return frame  # sample(frac=1.0) would shuffle the canonical order
    return frame.sample(frac=sample_frac, random_state=random_state)


@model.trainer
def trainer(
    estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame
) -> LogisticRegression:
    return estimator.fit(features, target.squeeze())


@model.predictor
def predictor(estimator: LogisticRegression, features: pd.DataFrame) -> list:
    return [float(x) for x in estimator.predict(features)]


@model.evaluator
def evaluator(
    estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame
) -> float:
    return float(estimator.score(features, target.squeeze()))


# gateway events (GET /, GET /health, POST /predict)
handler = gateway_handler(model)

# object-store upload events: predict each uploaded JSON feature file and
# write <key>.predictions.json back to the same bucket
store = LocalObjectStore("./objectstore")
on_upload = object_event_handler(model, store)


if __name__ == "__main__":
    estimator, metrics = model.train(hyperparameters={"max_iter": 5000})
    print(f"metrics: {metrics}")
    model.save("model.joblib")
