"""Scaffolded smoke test: quantized weights materialize, ragged prompts
generate the configured number of tokens."""

import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent.parent))

import app


def test_generate_ragged_prompts():
    params, _ = app.model.train()
    out = app.model.predict(features=[[1, 5, 9], [2, 4, 6, 8]])
    arr = np.asarray(out)
    assert arr.shape == (2, app.MAX_NEW_TOKENS)
