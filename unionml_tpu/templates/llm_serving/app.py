"""{{app_name}}: LLM generation served as a unionml-tpu microservice.

The fifth template: a Llama-family causal LM behind the standard
Dataset/Model spec — prompts come in as token-id lists over HTTP, the
predictor pads them into bucketed shapes and runs the jitted
prefill + scan-decode generator (optionally int8-quantized for serving).

Run:
    python app.py                       # init + save (random weights demo)
    unionml-tpu serve app:model --model-path model.utpu
    curl -X POST localhost:8000/predict \
         -d '{"features": [[1, 5, 9], [2, 4, 6, 8]]}'

Swap ``LlamaConfig.tiny`` for ``LlamaConfig.llama3_8b()`` plus trained
weights for the real thing; on a multi-chip slice shard the params with
``LLAMA_QUANT_PARTITION_RULES`` over a ``tensor`` mesh axis.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from unionml_tpu import Dataset, Model
from unionml_tpu.models import (
    LLAMA_QUANT_PATTERNS,
    Llama,
    LlamaConfig,
    make_lm_predictor,
    serving_params,
    quantize_params,
)

MAX_NEW_TOKENS = 32
QUANTIZE = True  # int8 weight-only serving (~1.3-1.5x faster decode)

config = LlamaConfig.tiny(vocab_size=512)
module = Llama(config)
serving_config = dataclasses.replace(config, quantized=True) if QUANTIZE else config
serving_module = Llama(serving_config)

dataset = Dataset(name="{{app_name}}_dataset")


@dataset.reader
def reader() -> list:
    # LMs have no training dataset here; the reader exists so the spec
    # compiles (fine-tuning would read token corpora instead)
    return [[1, 2, 3]]


@dataset.feature_loader
def feature_loader(raw: list) -> list:
    # ragged token-id prompts stay lists; the predictor buckets/pads them
    return raw


model = Model(name="{{app_name}}", dataset=dataset)


@model.init
def init(hyperparameters: dict) -> dict:
    params = jax.jit(module.init)(
        jax.random.PRNGKey(hyperparameters.get("seed", 0)),
        jnp.zeros((1, 8), jnp.int32),
    )["params"]
    if QUANTIZE:
        params = quantize_params(params, LLAMA_QUANT_PATTERNS)
    # one-time bf16 cast: decode re-reads the whole weight tree per token,
    # fp32 masters double that traffic (models.serving_params)
    return serving_params(params)


@model.trainer
def trainer(params: dict, features: list, targets: list) -> dict:
    # serving-only app: "training" materializes the (quantized) weights;
    # see the basic_tpu template for a real train_step
    return params


_generate = make_lm_predictor(
    serving_module, max_new_tokens=MAX_NEW_TOKENS, bucket_lens=(16, 32, 64, 128)
)


@model.predictor
def predictor(params: dict, prompts: list) -> list:
    return _generate(params, prompts)


# serve with every executable pre-compiled (first-hit shapes otherwise
# stall live requests behind multi-second XLA compiles):
#   serving = ServingApp(model, batch=True, row_lists=True,
#                        warmup=lambda p: _generate.warmup(p, max_batch=8))
#   serving.serve()


if __name__ == "__main__":
    params, _ = model.train()
    out = model.predict(features=[[1, 5, 9], [2, 4, 6, 8]])
    print(f"generated: {np.asarray(out).shape}")
    model.save("model.utpu")
