"""Scaffolded smoke test: the spec trains and predicts end to end."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import app


def test_train_and_predict():
    estimator, metrics = app.model.train(hyperparameters={"max_iter": 200})
    assert metrics["test"] > 0.8
    preds = app.model.predict(sample_frac=0.05, random_state=1)
    assert isinstance(preds, list) and preds
