"""{{app_name}}: a unionml-tpu app (sklearn digits quickstart).

Template parity: reference templates/basic/{{cookiecutter.app_name}}/app.py.
Train locally with ``python app.py``, serve with
``unionml-tpu serve app:model --model-path model.joblib``.
"""

import pandas as pd
from sklearn.linear_model import LogisticRegression

from unionml_tpu import Dataset, Model

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.2, shuffle=True, targets=["target"])
model = Model(name="{{app_name}}", init=LogisticRegression, dataset=dataset)


@dataset.reader
def reader(sample_frac: float = 1.0, random_state: int = 42) -> pd.DataFrame:
    from sklearn.datasets import load_digits

    frame = load_digits(as_frame=True).frame
    if sample_frac >= 1.0:
        return frame  # sample(frac=1.0) would shuffle the canonical order
    return frame.sample(frac=sample_frac, random_state=random_state)


@model.trainer
def trainer(
    estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame
) -> LogisticRegression:
    return estimator.fit(features, target.squeeze())


@model.predictor
def predictor(estimator: LogisticRegression, features: pd.DataFrame) -> list:
    return [float(x) for x in estimator.predict(features)]


@model.evaluator
def evaluator(
    estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame
) -> float:
    return float(estimator.score(features, target.squeeze()))


if __name__ == "__main__":
    estimator, metrics = model.train(hyperparameters={"max_iter": 5000})
    print(f"metrics: {metrics}")
    model.save("model.joblib")
