"""Scaffolded smoke test: the jit train_step trains and predicts."""

import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).parent.parent))

import app


def test_train_step_and_jit_predictor():
    state, metrics = app.model.train(
        hyperparameters={"hidden": 128, "learning_rate": 1e-3},
        trainer_kwargs={"num_epochs": 5, "batch_size": 64},
    )
    assert metrics["test"] > 0.7
    preds = app.model.predict(features=np.zeros((2, 64), np.float32))
    assert np.asarray(preds).shape == (2,)
