"""{{app_name}}: a TPU-native unionml-tpu app (flax MLP on MNIST-style digits).

The trainer is a jittable per-batch step compiled over a device mesh —
the north-star path (no reference counterpart; the reference's templates
are CPU sklearn/torch apps).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn
from flax.training import train_state

from unionml_tpu import Dataset, Model
from unionml_tpu.parallel import ShardingConfig

dataset = Dataset(name="{{app_name}}_dataset", test_size=0.2)
model = Model(name="{{app_name}}", dataset=dataset)


@dataset.reader
def reader() -> dict:
    from sklearn.datasets import load_digits

    digits = load_digits()
    return {
        "features": digits.data.astype(np.float32) / 16.0,
        "targets": digits.target.astype(np.int32),
    }


@dataset.splitter
def splitter(data: dict, test_size: float, shuffle: bool, random_state: int):
    n = len(data["features"])
    idx = np.arange(n)
    if shuffle:
        np.random.default_rng(random_state).shuffle(idx)
    k = int(n * (1 - test_size))
    tr, te = idx[:k], idx[k:]
    return (
        {"features": data["features"][tr], "targets": data["targets"][tr]},
        {"features": data["features"][te], "targets": data["targets"][te]},
    )


@dataset.parser
def parser(data: dict, features, targets):
    return (data["features"], data["targets"])


class MLP(nn.Module):
    hidden: int = 128

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(10)(x)


@model.init
def init(hyperparameters: dict) -> train_state.TrainState:
    module = MLP(hidden=hyperparameters.get("hidden", 128))
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 64)))["params"]
    return train_state.TrainState.create(
        apply_fn=module.apply,
        params=params,
        tx=optax.adam(hyperparameters.get("learning_rate", 1e-3)),
    )


@model.train_step(sharding=ShardingConfig(data=-1))
def train_step(state, batch):
    x, y = batch

    def loss_fn(params):
        logits = state.apply_fn({"params": params}, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    return state.apply_gradients(grads=grads), {"loss": loss}


@model.predictor(jit=True)
def predictor(state: train_state.TrainState, features: np.ndarray) -> jnp.ndarray:
    return jnp.argmax(state.apply_fn({"params": state.params}, features), axis=-1)


@model.evaluator
def evaluator(state: train_state.TrainState, features: np.ndarray, targets: np.ndarray) -> float:
    logits = state.apply_fn({"params": state.params}, features)
    return float((jnp.argmax(logits, axis=-1) == targets).mean())


if __name__ == "__main__":
    state, metrics = model.train(
        hyperparameters={"hidden": 128, "learning_rate": 1e-3},
        trainer_kwargs={"num_epochs": 10, "batch_size": 128},
    )
    print(f"metrics: {metrics}")
    model.save("model.utpu")
