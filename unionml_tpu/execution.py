"""Device execution engine: jit/pjit compilation of step functions.

This is the TPU-native execution substrate the reference delegates to
flytekit's local executor (reference: unionml/model.py:425-440 runs the
user trainer opaquely). Here, a registered ``train_step`` is compiled once
with ``jax.jit`` — optionally over a ``jax.sharding.Mesh`` with
NamedSharding in/out specs — and driven by a host batching loop that:

- keeps shapes **static** (remainder batches are dropped) so XLA compiles
  exactly one executable,
- **donates** the state buffers so parameter memory is reused in-place,
- streams batches through the double-buffered device feed
  (:mod:`unionml_tpu.data.pipeline`) to overlap host→HBM transfer with
  compute.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Callable, Iterable, Optional

import numpy as np

from unionml_tpu import telemetry
from unionml_tpu._logging import logger


def publish_hbm_gauges(registry: Optional[Any] = None) -> int:
    """Publish each local device's ``memory_stats()['bytes_in_use']`` as
    the ``unionml_trainer_hbm_bytes_in_use{device=...}`` gauge; returns
    the number of devices that reported. Safe everywhere: backends
    without memory stats (CPU, some plugins) simply publish nothing.
    """
    import jax

    reg = registry if registry is not None else telemetry.get_registry()
    gauge = reg.gauge(
        "unionml_trainer_hbm_bytes_in_use",
        "Device memory in use per jax.Device.memory_stats().",
        ("device",),
    )
    published = 0
    for device in jax.local_devices():
        try:
            stats = device.memory_stats()
        except Exception:
            stats = None
        if stats and "bytes_in_use" in stats:
            gauge.labels(device=str(device.id)).set(float(stats["bytes_in_use"]))
            published += 1
    return published


def _publish_loss(metrics: Any, gauge: Any) -> None:
    """Set ``gauge`` from the first scalar metric leaf whose path names
    'loss' (readback — call only at a window boundary that already
    syncs)."""
    import jax

    try:
        flat, _ = jax.tree_util.tree_flatten_with_path(metrics)
        for path, leaf in flat:
            name = jax.tree_util.keystr(path).lower()
            if "loss" in name and np.ndim(leaf) == 0:
                gauge.set(float(np.asarray(leaf)))
                return
    except Exception:  # metrics trees are user-shaped: never fail a step
        pass


@functools.lru_cache(maxsize=128)
def _jitted(
    fn: Callable,
    donate_state: bool,
    donate_batch: bool = False,
    overlap: Any = None,
):
    """Per-function jit cache (bounded: entries pin user closures + XLA
    executables, which can be large for big models). Interactive sessions
    that re-define step functions churn entries that pin executables until
    eviction — call :func:`clear_jit_cache` to drop them eagerly.

    ``donate_batch`` donates the batch argument too (the double-buffer
    prefetch contract: every fed batch is a fresh device buffer consumed
    exactly once, so XLA may recycle it for step temporaries — HBM holds
    the in-flight batches, not the consumed ones). ``overlap`` (a
    :class:`~unionml_tpu.models.train.GradOverlap` or None) is part of
    the cache key ONLY: the overlap strategy is read at trace time from
    the ambient :func:`~unionml_tpu.models.train.grad_overlap_scope`,
    and keying on it keeps serial and overlapped executables from
    aliasing when the same step function is trained both ways."""
    import jax

    donate = (0,) if donate_state else ()
    if donate_batch:
        donate = donate + (1,)
    return jax.jit(fn, donate_argnums=donate)


def clear_jit_cache() -> None:
    """Drop every cached jit wrapper (and the XLA executables + user
    closures it pins). Useful in long-lived interactive sessions after
    re-defining step functions or models."""
    _jitted.cache_clear()


def jit_predictor(fn: Callable) -> Callable:
    """jit-compile a predictor body ``(model_object, features) -> preds``.

    Shares the bounded per-function cache; XLA's own cache handles
    shape/dtype polymorphism across calls.
    """
    return _jitted(fn, False)


def resolve_grad_overlap(sharding: Any, accumulate_steps: int) -> Any:
    """The :class:`~unionml_tpu.models.train.GradOverlap` strategy for a
    trainer run with ``overlap_grads=True`` — ONE selection rule shared
    by :func:`run_step_trainer` and the elastic trainer.

    - ``accumulate_steps == 1``: None (no microbatch pipeline exists to
      overlap; the step is one fused forward/backward).
    - pure data parallelism (every mesh axis but ``data`` trivial, no
      partition rules): ``mode="shard_map"`` — the scan runs under
      ``shard_map`` and issues explicit deferred
      :func:`~unionml_tpu.parallel.collectives.bucketed_psum` chunks.
    - anything else (fsdp/tensor/… sharded params, or no mesh at all):
      ``mode="defer"`` — GSPMD keeps inserting the collectives and the
      scan defers their consumption one microbatch, the structure
      XLA's collective pipeliner hides latency in.
    """
    from unionml_tpu.models.train import GradOverlap

    if accumulate_steps <= 1:
        logger.info(
            "overlap_grads: accumulate_steps=1 has no microbatch "
            "pipeline to overlap — running the serial step"
        )
        return None
    if sharding is None:
        return GradOverlap(mode="defer")
    mesh = sharding.mesh()
    model_axes = {
        name: size for name, size in dict(mesh.shape).items()
        if name != "data" and size > 1
    }
    if not model_axes and not tuple(sharding.rules) and mesh.shape.get("data", 1) > 1:
        return GradOverlap(mode="shard_map", mesh=mesh, axes=("data",))
    return GradOverlap(mode="defer")


def _num_examples(features: Any) -> int:
    import jax

    leaves = jax.tree_util.tree_leaves(features)
    if not leaves:
        raise ValueError("train_step features pytree has no array leaves")
    return int(leaves[0].shape[0])


def _slice_batch(data: Any, idx: np.ndarray) -> Any:
    import jax

    return jax.tree_util.tree_map(lambda x: np.asarray(x)[idx], data)


def to_microbatches(batch: Any, accumulate_steps: int, batch_size: int) -> Any:
    """Reshape a fed batch's leaves to ``[accumulate_steps, batch_size, ...]``.

    The gradient-accumulation feeding contract shared by
    :func:`run_step_trainer` and the elastic trainer: raises a clear
    error when the leading dim isn't ``accumulate_steps * batch_size``
    (e.g. a stream still yielding un-accumulated batches), and
    materializes list-like leaves once.
    """
    import jax

    feed_rows = accumulate_steps * batch_size

    def reshape(x):
        if not hasattr(x, "reshape"):
            # list-like leaf: materialize once; device-resident arrays
            # reshape in place (np.asarray here would round-trip them
            # device->host->device every step)
            x = np.asarray(x)
        if x.shape[0] != feed_rows:
            raise ValueError(
                f"accumulation batch has leading dim {x.shape[0]}, "
                f"expected accumulate_steps * batch_size = {feed_rows}"
            )
        return x.reshape((accumulate_steps, batch_size) + x.shape[1:])

    return jax.tree_util.tree_map(reshape, batch)


def is_stream(features: Any) -> bool:
    """The trainer-feed streaming rule, ONE home (run_step_trainer and
    the checkpoint_dir elastic route must agree or a stream silently
    np.asarray's into garbage): streams are callables (fresh iterable
    per epoch), iterators (one pass), or re-iterable loader objects
    (DataLoader-likes). Pytree containers and arrays are NOT streams —
    they carry the (features[, targets]) array contract."""
    return callable(features) or (
        hasattr(features, "__iter__")
        and not isinstance(features, (dict, list, tuple, str, bytes))
        and not hasattr(features, "__array__")
        and not hasattr(features, "shape")
    )


def batch_indices(
    n: int, batch_size: int, *, shuffle: bool, seed: int, drop_remainder: bool = True
) -> Iterable[np.ndarray]:
    """Static-shape batch index generator. Remainder batches are dropped so
    the jitted step sees one shape (no XLA recompiles)."""
    order = np.arange(n)
    if shuffle:
        np.random.default_rng(seed).shuffle(order)
    n_batches = n // batch_size if drop_remainder else -(-n // batch_size)
    if n_batches == 0 and n > 0:
        # fewer examples than batch_size: single undersized batch
        yield order
        return
    for i in range(n_batches):
        yield order[i * batch_size : (i + 1) * batch_size]


def run_step_trainer(
    *,
    step_fn: Callable,
    state: Any,
    features: Any,
    targets: Any = None,
    num_epochs: int = 1,
    batch_size: int = 32,
    seed: int = 0,
    sharding: Any = None,
    donate_state: bool = True,
    accumulate_steps: int = 1,
    overlap_grads: bool = False,
    double_buffer: bool = False,
    donate_batch: Optional[bool] = None,
    profile_dir: Optional[str] = None,
    registry: Optional[Any] = None,
    goodput: Any = None,
    measure_device_time: bool = False,
    skew_every: int = 50,
) -> Any:
    """Synthesized trainer loop around a jittable per-batch step.

    ``step_fn(state, batch) -> (state, metrics)`` where ``batch`` is
    ``(features, targets)`` sliced along the leading axis (or just
    ``features`` when no targets exist, e.g. self-supervised LM batches).

    ``accumulate_steps=N`` (gradient accumulation): each fed batch holds
    ``N * batch_size`` examples reshaped to a leading microbatch axis
    ``[N, batch_size, ...]``, and the step must scan it with ONE
    optimizer update (the zoo factories' ``accumulate_steps`` builds
    such steps — :func:`unionml_tpu.models.train.accumulated_value_and_grad`).
    Under a ``sharding`` config the microbatch axis stays unsharded
    (each device scans its own microbatch shards); streams must yield
    batches of ``N * batch_size`` rows.

    With a ``sharding`` config (:class:`unionml_tpu.parallel.ShardingConfig`)
    the step is compiled under its mesh: state placed per the config's param
    spec, batches sharded along the data axis, XLA inserting the gradient
    ``psum`` over ICI automatically.

    **Streaming**: ``features`` may instead be an iterator/generator of
    ready batches (one pass; ``num_epochs`` must be 1) or a zero-arg
    callable returning one iterable per epoch (SURVEY.md §7.4 "reader →
    host prefetch, made streaming"). Each yielded item is fed to the step
    as-is (build ``(x, y)`` tuples in the stream); batch shapes must be
    constant or XLA recompiles per shape. ``targets`` must be None.

    **Telemetry**: the loop publishes into the shared
    :mod:`unionml_tpu.telemetry` registry (``registry=`` overrides):
    ``unionml_trainer_step_ms`` (per-step host dispatch wall time;
    window boundaries force a readback so windowed numbers stay honest),
    ``unionml_trainer_loss`` (last scalar 'loss' metric at a window
    boundary), ``unionml_trainer_samples_per_sec`` (windowed StepTimer
    rate), steps/examples counters, and per-device
    ``unionml_trainer_hbm_bytes_in_use`` gauges from
    ``jax.Device.memory_stats()`` — the same registry the serving
    layers scrape through ``GET /metrics``.

    ``measure_device_time=True`` adds a ``block_until_ready`` sync
    point after EVERY step dispatch so ``unionml_trainer_step_ms``
    samples real device step latency instead of host dispatch time
    (async dispatch makes the default per-step sample an enqueue
    measurement; only window boundaries force a readback). Opt-in: the
    sync defeats dispatch pipelining, so expect a small throughput
    cost — it exists for latency attribution, not production runs.

    **Goodput accounting** (docs/observability.md "Training
    goodput"): ``goodput=True`` (or a
    :class:`~unionml_tpu.goodput.GoodputTracker` instance) attributes
    the loop's wall time into compute vs. badput buckets — data-wait
    and host→device dispatch in the prefetch feed, compile/recompile
    (via the program tracker's compile events), jitted compute — and
    publishes ``unionml_train_goodput_ratio`` /
    ``unionml_train_badput_seconds_total{cause}``, per-phase trace
    spans, the step-time regression detector, and (every
    ``skew_every`` steps under ``jax.process_count() > 1``) per-host
    step-skew gauges with straggler flight events.

    **Overlapped training** (docs/performance.md "Overlapped
    training"): ``overlap_grads=True`` restructures the gradient
    accumulation so the dp/fsdp all-reduce of microbatch *i* overlaps
    the backward of microbatch *i+1* (:func:`resolve_grad_overlap`
    picks the shard_map bucketed-psum or GSPMD deferred-consumption
    form; loss trajectories stay bit-identical to the serial scan —
    no-op at ``accumulate_steps=1`` or for steps not built on
    :func:`~unionml_tpu.models.train.accumulated_value_and_grad`).
    ``double_buffer=True`` moves the whole data feed (host batch pull
    + device transfer dispatch) to a background thread, draining the
    ``data_wait``/``host_to_device`` badput buckets, and — unless
    ``donate_batch=False`` — donates the fed batch buffers to the step
    so prefetch depth does not double batch HBM. Donation is only
    unsafe for sources that YIELD already-device-resident arrays they
    retain (the feed would hand the same buffer to the step twice);
    host-side sources (numpy arrays, loaders, generators) are always
    safe. In overlap mode the trailing ``block_until_ready`` drain
    still lands in the ``compute`` bucket — overlapped transfers are
    never misattributed to ``data_wait``.
    """
    import jax

    streaming = is_stream(features)
    if streaming:
        if targets is not None:
            raise ValueError(
                "streaming trainers take batches from `features` alone — "
                "yield (x, y) tuples from the stream instead of passing targets"
            )
        if hasattr(features, "__next__") and num_epochs != 1:
            raise ValueError(
                "a one-shot batch iterator cannot be replayed for "
                f"num_epochs={num_epochs}; pass a callable returning a fresh "
                "iterable per epoch"
            )
    n = 0 if streaming else _num_examples(features)
    has_targets = targets is not None

    if accumulate_steps < 1:
        raise ValueError(f"accumulate_steps must be >= 1, got {accumulate_steps}")
    feed_rows = batch_size * accumulate_steps
    overlap = (
        resolve_grad_overlap(sharding, accumulate_steps)
        if overlap_grads else None
    )
    if donate_batch is None:
        donate_batch = double_buffer
    if accumulate_steps > 1:
        if not streaming and n < feed_rows:
            raise ValueError(
                "gradient accumulation needs at least accumulate_steps * "
                f"batch_size = {feed_rows} examples per step, got {n}"
            )
        if sharding is not None:
            sharding = sharding.microbatched()

        def _to_microbatches(batch: Any) -> Any:
            return to_microbatches(batch, accumulate_steps, batch_size)

    if sharding is not None:
        from unionml_tpu.parallel import compile_step

        step, state = compile_step(
            step_fn, state, sharding=sharding,
            donate_state=donate_state, donate_batch=donate_batch,
        )
    else:
        step = _jitted(step_fn, donate_state, donate_batch, overlap)

    from unionml_tpu.data.pipeline import prefetch_to_device

    def _is_plain_array(x: Any) -> bool:
        return not isinstance(x, (dict, list, tuple)) and hasattr(x, "__array__")

    def host_batches():
        if streaming:
            for epoch in range(num_epochs):
                stream = features() if callable(features) else iter(features)
                got = 0
                for item in stream:
                    got += 1
                    yield _to_microbatches(item) if accumulate_steps > 1 else item
                if got == 0:
                    # silent zero-batch epochs under-train with no signal:
                    # an already-exhausted iterator, or a callable returning
                    # the SAME exhausted iterator each epoch
                    raise ValueError(
                        "streaming source yielded no batches in epoch "
                        f"{epoch + 1}/{num_epochs}. A callable must return a "
                        "FRESH iterable per call (a lambda closing over one "
                        "generator replays an exhausted stream); an iterator "
                        "must not be consumed before training"
                    )
            return
        # fast path: plain (features[, targets]) arrays go through the
        # native threaded batch loader. copy=True: device_put only
        # ENQUEUES the host→HBM transfer (PJRT may read the host buffer
        # after returning), so zero-copy staging buffers must not be
        # recycled under an in-flight DMA
        if (
            _is_plain_array(features)
            and (not has_targets or _is_plain_array(targets))
            and n >= feed_rows
        ):
            from unionml_tpu.data.native import BatchLoader

            arrays = [np.asarray(features)]
            if has_targets:
                arrays.append(np.asarray(targets))
            loader = BatchLoader(
                arrays, batch_size=feed_rows, seed=seed, shuffle=True,
                drop_remainder=True, copy=True,
            )
            try:
                for epoch in range(num_epochs):
                    for batch in loader.epoch(epoch):
                        out = batch if has_targets else batch[0]
                        yield _to_microbatches(out) if accumulate_steps > 1 else out
            finally:
                loader.close()
            return
        for epoch in range(num_epochs):
            for idx in batch_indices(n, feed_rows, shuffle=True, seed=seed + epoch):
                xb = _slice_batch(features, idx)
                out = (xb, _slice_batch(targets, idx)) if has_targets else xb
                yield _to_microbatches(out) if accumulate_steps > 1 else out

    from unionml_tpu.diagnostics import StepTimer, trace

    reg = registry if registry is not None else telemetry.get_registry()
    h_step = reg.histogram(
        "unionml_trainer_step_ms",
        "Per-step wall time. Default: host dispatch (async enqueue; "
        "window boundaries force a data-dependent readback so windowed "
        "rates measure compute). With measure_device_time= every step "
        "syncs, so samples are real device step latency.",
    )
    g_loss = reg.gauge(
        "unionml_trainer_loss",
        "Last scalar 'loss' metric read back at a window boundary.",
    )
    g_rate = reg.gauge(
        "unionml_trainer_samples_per_sec",
        "Windowed training throughput (latest StepTimer window).",
    )
    c_steps = reg.counter(
        "unionml_trainer_steps_total", "Train steps dispatched.",
    )
    c_examples = reg.counter(
        "unionml_trainer_examples_total", "Training examples consumed.",
    )

    from unionml_tpu.goodput import (
        GoodputTracker, allgather_step_times, phase_scope,
    )

    tracker = None
    if goodput:
        tracker = (
            goodput if isinstance(goodput, GoodputTracker)
            else GoodputTracker(registry=reg)
        )

    # program introspection (docs/observability.md): compile events on
    # the step record XLA cost-analysis flops/bytes + compile time, and
    # the unionml_program_mfu_ratio{component="trainer",
    # program="trainer.step"} gauge reports live MFU against the device
    # peak — the same scrape surface as the serving layers
    from unionml_tpu.introspection import ProgramTracker

    step = ProgramTracker(
        registry=reg, component="trainer",
        on_compile=tracker.note_compile_ms if tracker is not None else None,
    ).wrap("trainer.step", step)

    # the overlap scope must be open while the loop runs: jit traces the
    # step at its FIRST call, and accumulated_value_and_grad reads the
    # ambient GradOverlap at trace time. Imported BEFORE tracker.start():
    # a cold models.train import is tens of ms of setup the goodput
    # identity should not have to explain
    from unionml_tpu.models.train import grad_overlap_scope

    timer = StepTimer()
    steps = 0
    metrics = None
    if tracker is not None:
        tracker.start()
    ctx = trace(profile_dir) if profile_dir else contextlib.nullcontext()
    overlap_ctx = (
        grad_overlap_scope(overlap) if overlap is not None
        else contextlib.nullcontext()
    )
    # finish() must run on the exception path too (mirrors elastic.py):
    # a raising stream would otherwise leave the trainer trace timeline
    # open forever, and a retry with the same tracker would count the
    # crash-to-retry gap as unattributed wall time
    feed = prefetch_to_device(
        host_batches(), sharding=sharding, goodput=tracker,
        double_buffer=double_buffer,
    )
    try:
        with ctx, overlap_ctx, contextlib.closing(feed):
            for batch in feed:
                t_step = time.perf_counter()
                with phase_scope(tracker, "compute"):
                    state, metrics = step(state, batch)
                    window_closed = timer.closes_window()
                    if measure_device_time:
                        # opt-in sync point: the step_ms sample below then
                        # measures real device latency, not host dispatch
                        jax.block_until_ready((state, metrics))
                    elif window_closed:
                        # force a readback data-dependent on this step so the
                        # window measures compute, not async dispatch (step()
                        # only enqueues work; see BASELINE.md on tunnel timing)
                        leaves = jax.tree_util.tree_leaves(metrics)
                        if leaves:
                            np.asarray(leaves[0])
                # the sync above is part of step time; the publishes below
                # are host-side bookkeeping and must not inflate the sample
                step_s = time.perf_counter() - t_step
                h_step.observe(step_s * 1e3)
                if tracker is not None:
                    # under async dispatch the window-boundary readback
                    # drains a whole window of device work into this one
                    # sample — not comparable to the dispatch-scale
                    # baseline, so keep it out of the regression detector
                    # (with measure_device_time every step syncs and all
                    # samples are comparable)
                    tracker.step_complete(
                        step_s,
                        detect=measure_device_time or not window_closed,
                    )
                    if skew_every > 0 and (steps + 1) % skew_every == 0:
                        # multihost sync point only (process_count > 1):
                        # single-host runs never pay a collective here
                        times = allgather_step_times(step_s)
                        if times is not None:
                            tracker.record_step_skew(steps + 1, times)
                if window_closed:
                    # the window already synced: piggyback the loss/HBM
                    # publishes on it instead of adding readbacks per step
                    _publish_loss(metrics, g_loss)
                    publish_hbm_gauges(reg)
                # actual leading dim (streamed batches may differ from batch_size);
                # with accumulation the example count spans the two leading axes
                rows = next(
                    (
                        leaf.shape[0] * leaf.shape[1]
                        if accumulate_steps > 1 and getattr(leaf, "ndim", 0) >= 2
                        else leaf.shape[0]
                        for leaf in jax.tree_util.tree_leaves(batch)
                        if getattr(leaf, "ndim", 0) >= 1
                    ),
                    batch_size,
                )
                timer.tick(rows)
                c_steps.inc()
                c_examples.inc(rows)
                if timer.rates:
                    g_rate.set(timer.rates[-1])
                steps += 1
        if steps:
            # the trailing drain is device compute still in flight
            with phase_scope(tracker, "compute"):
                jax.block_until_ready(state)
            last = jax.tree_util.tree_map(lambda x: np.asarray(x).item() if np.ndim(x) == 0 else x, metrics)
            _publish_loss(metrics, g_loss)
            publish_hbm_gauges(reg)
            rate = timer.summary().get("samples_per_sec_median")
            if rate:
                g_rate.set(rate)
            suffix = f", ~{rate:.0f} samples/sec" if rate else ""
            logger.info(f"step trainer: {steps} steps, final metrics: {last}{suffix}")
    finally:
        if tracker is not None:
            tracker.finish()
    return state
