"""Instance tracking for stage rehydration.

The reference leans on ``flytekit.core.tracker.TrackedInstance`` so that a
dynamically generated task can be serialized as a pointer ``(app module,
variable name, generator method)`` and regenerated inside a remote container
(reference: unionml/task_resolver.py:16-31). We implement the same idea
natively: a :class:`TrackedInstance` records the module it was instantiated
in at ``__init__`` time and lazily discovers the module-level variable name
that refers to it.
"""

from __future__ import annotations

import importlib
import inspect
import sys
from typing import Optional, Tuple


class TrackedInstance:
    """Records instantiation module so instances can be found by name later."""

    def __init__(self, *args, **kwargs):
        self._instantiated_in: Optional[str] = None
        self._lhs: Optional[str] = None
        frame = inspect.currentframe()
        # walk out of unionml_tpu-internal frames (subclass __init__ chains)
        while frame is not None:
            mod = frame.f_globals.get("__name__", "")
            if not mod.startswith("unionml_tpu"):
                self._instantiated_in = mod
                break
            frame = frame.f_back

    @property
    def instantiated_in(self) -> Optional[str]:
        return self._instantiated_in

    def find_lhs(self) -> str:
        """Find the module-level variable name bound to this instance."""
        if self._lhs is not None:
            return self._lhs
        if self._instantiated_in and self._instantiated_in in sys.modules:
            module = sys.modules[self._instantiated_in]
            for k, v in vars(module).items():
                if v is self:
                    self._lhs = k
                    return k
        raise ValueError(
            f"Could not find a module-level variable referencing {self!r} in "
            f"module {self._instantiated_in!r}. Assign the instance to a "
            "module-level variable so it can be rehydrated remotely."
        )

    def loader_path(self) -> Tuple[str, str]:
        """``(module, variable)`` pointer used by the stage resolver."""
        return self._instantiated_in or "", self.find_lhs()


def load_instance(module_name: str, var_name: str) -> TrackedInstance:
    """Re-import ``module_name`` and return its ``var_name`` instance.

    This is the rehydration half of the resolver trick
    (reference: unionml/task_resolver.py:16-21).
    """
    module = importlib.import_module(module_name)
    return getattr(module, var_name)
