"""Preemption-safe training: periodic checkpoints + deterministic resume.

The reference's failure story is minimal (SURVEY.md §5.3: a dirty-git
guard, reference: unionml/remote.py:44-48, plus retries delegated to
Flyte). On TPU slices, preemption is routine, so the rebuild makes
checkpoint-based restart a framework primitive:

- training position is a ``(epoch, step)`` coordinate; the data order is
  a pure function of ``(seed, epoch)`` (the splitmix64 permutation shared
  by the native loader and its numpy fallback — see
  :mod:`unionml_tpu.data.native`), so restoring the state pytree and
  seeking the loader reproduces the exact batch stream;
- :func:`run_elastic_trainer` checkpoints every ``checkpoint_every``
  steps (global step index in the checkpoint name encodes the position)
  and on start resumes from the newest checkpoint under ``checkpoint_dir``;
- a killed-and-restarted run reaches the bit-identical final state of an
  uninterrupted run (tested by fault injection in
  tests/unit/test_diagnostics.py).

Checkpointing is **async by default**
(:func:`~unionml_tpu.checkpoint.make_checkpoint_manager`): ``save``
stalls the loop for the device→host snapshot only, the serialize/
write/commit overlaps the following steps on a background thread, and
restore refuses torn checkpoints — a kill mid-commit resumes from the
previous complete step. Batches flow through
:func:`~unionml_tpu.data.pipeline.prefetch_to_device` (the
``double_buffer`` knob moves the whole feed onto a background thread),
and ``overlap_grads`` overlaps the gradient all-reduce with backward
compute — the same overlapped-training surface as
:func:`~unionml_tpu.execution.run_step_trainer`
(docs/performance.md "Overlapped training").
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Optional, Sequence, Tuple

import numpy as np

from unionml_tpu._logging import logger
from unionml_tpu.checkpoint.async_writer import make_checkpoint_manager
from unionml_tpu.data.native import BatchLoader
from unionml_tpu.data.pipeline import prefetch_to_device
from unionml_tpu.goodput import phase_scope as _phase


class Preemption(RuntimeError):
    """Raised by fault injectors to simulate a slice preemption."""


_STREAM_END = object()  # next(it) default: exhaustion sentinel


def run_elastic_trainer(
    *,
    step_fn: Callable,
    state: Any,
    arrays: Optional[Sequence[np.ndarray]] = None,
    stream: Optional[Callable] = None,
    num_steps: Optional[int] = None,
    checkpoint_dir: str,
    num_epochs: int = 1,
    batch_size: int = 32,
    seed: int = 0,
    checkpoint_every: int = 100,
    max_to_keep: int = 3,
    checkpoint_backend: str = "auto",
    sharding: Any = None,
    donate_state: bool = True,
    accumulate_steps: int = 1,
    overlap_grads: bool = False,
    double_buffer: bool = False,
    donate_batch: Optional[bool] = None,
    fault_hook: Optional[Callable[[int], None]] = None,
    goodput: Any = None,
) -> Tuple[Any, int]:
    """Train with periodic checkpoints, resuming from the newest one.

    ``step_fn(state, batch) -> (state, metrics)`` jittable; ``arrays`` is
    ``(features,)`` or ``(features, targets)`` row-aligned numpy arrays.
    Returns ``(final_state, global_step)``. ``fault_hook(global_step)``
    is a test seam: it runs after each step and may raise to simulate
    preemption.

    ``accumulate_steps=N``: gradient accumulation — each global step
    consumes ``N * batch_size`` rows reshaped to a leading microbatch
    axis (the ``run_step_trainer`` contract; build the step with a zoo
    factory's ``accumulate_steps``). The global step COUNT includes the
    accumulation, so resume points stay aligned with optimizer updates.

    Global step indexes the stream ``epoch * steps_per_epoch + batch``;
    checkpoints are written under ``checkpoint_dir/step_{global_step}``
    where the state has already consumed batch ``global_step - 1``.

    **Checkpointing** is async by default: ``checkpoint_backend``
    ("auto" / "async" / "orbax" / "sync") picks the
    :class:`~unionml_tpu.checkpoint.AsyncCheckpointManager` (host
    snapshot is the only save stall; background commit with atomic
    rename + commit marker; restore refuses torn checkpoints) or the
    Orbax sharded manager (multi-process meshes, or a ``checkpoint_dir``
    that already holds Orbax-format steps).

    **Overlapped training** (docs/performance.md "Overlapped
    training"): ``overlap_grads=True`` overlaps the dp/fsdp gradient
    all-reduce of microbatch *i* with the backward of microbatch *i+1*
    (loss trajectories bit-identical to the serial accumulate);
    ``double_buffer=True`` feeds batches from a background thread —
    host pull + device-transfer dispatch off the critical path — and
    donates the fed buffers to the step (``donate_batch=False`` opts
    out). Both compose with replay-after-preemption: the feed is
    rebuilt from the deterministic ``(seed, epoch)`` order on resume,
    so donated buffers are always fresh.

    **Streaming sources** (the execution.py streaming-trainer contract,
    made resumable): pass ``stream`` instead of ``arrays`` — a callable
    producing ready batches, treated as ONE step-indexed sequence
    (``num_epochs`` does not apply; bound it with ``num_steps`` or let it
    run to exhaustion). Resume semantics depend on the callable's
    signature:

    - ``stream(start_step)`` — seekable: called with the resume step, it
      must yield the batches from that position (e.g. reopen a file at a
      record offset). The cheap path.
    - ``stream()`` — replayable: called from the top and the first
      ``resume_step`` batches are SKIPPED host-side. Correct for any
      deterministic stream, but resume cost grows with position — prefer
      the seekable form for long runs.

    A final checkpoint is always written at exhaustion, so a finished
    stream run restores at its last step like an array run.

    **Goodput accounting**: ``goodput=True`` (or a
    :class:`~unionml_tpu.goodput.GoodputTracker`) attributes the
    loop's wall time (docs/observability.md "Training goodput") —
    jitted compute (including the trailing ``block_until_ready``
    drain, so overlapped transfers are never misattributed to
    ``data_wait``), ``data_wait`` on the batch feed, ``checkpoint``
    for the save stall on the critical path (with the async manager:
    snapshot only — the background commit publishes
    ``unionml_checkpoint_commit_ms`` instead), and ``preemption`` for
    the restore + replay cost of resuming after a kill: the price of
    the preemption, measured, so "how much did that eviction cost us"
    stops being a guess.
    """
    if (arrays is None) == (stream is None):
        raise ValueError("pass exactly one of arrays= or stream=")
    if accumulate_steps < 1:
        raise ValueError(f"accumulate_steps must be >= 1, got {accumulate_steps}")
    feed_rows = batch_size * accumulate_steps
    from unionml_tpu.execution import resolve_grad_overlap, to_microbatches
    # imported BEFORE tracker.start(): the first import of models.train
    # is tens of ms of cold module loading — setup cost, not training
    # wall time the goodput identity should have to explain
    from unionml_tpu.models.train import grad_overlap_scope

    overlap = (
        resolve_grad_overlap(sharding, accumulate_steps)
        if overlap_grads else None
    )
    if donate_batch is None:
        donate_batch = double_buffer
    if accumulate_steps > 1 and sharding is not None:
        sharding = sharding.microbatched()
    tracker = None
    if goodput:
        from unionml_tpu.goodput import GoodputTracker

        tracker = (
            goodput if isinstance(goodput, GoodputTracker) else GoodputTracker()
        )

    if sharding is not None:
        from unionml_tpu.parallel import compile_step

        step, state = compile_step(
            step_fn, state, sharding=sharding, donate_state=donate_state,
            donate_batch=donate_batch,
        )
    else:
        from unionml_tpu.execution import _jitted

        step = _jitted(step_fn, donate_state, donate_batch, overlap)

    if tracker is not None:
        # the wall window opens AFTER step construction, matching
        # run_step_trainer: compile_step's eager placement is build-time
        # setup, not loop wall time the identity must explain (first-call
        # jit compiles ARE in-window, debited to `compile` by the
        # ProgramTracker below; restore/replay lands in `preemption`)
        tracker.start()
        # compile-event detection on the jitted step: recompiles debit
        # the goodput compute bucket into the `compile` badput cause
        from unionml_tpu.introspection import ProgramTracker

        step = ProgramTracker(
            registry=tracker.registry, component="trainer",
            on_compile=tracker.note_compile_ms,
        ).wrap("trainer.elastic_step", step)

    # shared feeding contract with run_step_trainer: microbatch reshape
    # happens HOST-side in the feed (so prefetch placement sees the final
    # step shape), with to_microbatches' clear error on wrong leading dims
    if accumulate_steps > 1:
        def prepare(batch: Any) -> Any:
            return to_microbatches(batch, accumulate_steps, batch_size)
    else:
        def prepare(batch: Any) -> Any:
            return batch

    overlap_ctx = (
        grad_overlap_scope(overlap) if overlap is not None
        else contextlib.nullcontext()
    )

    if stream is not None:
        with overlap_ctx:
            return _run_stream(
                step, state, stream,
                checkpoint_dir=checkpoint_dir, num_steps=num_steps,
                checkpoint_every=checkpoint_every, max_to_keep=max_to_keep,
                checkpoint_backend=checkpoint_backend,
                fault_hook=fault_hook, tracker=tracker, prepare=prepare,
                sharding=sharding, double_buffer=double_buffer,
            )

    loader = BatchLoader(
        list(arrays), batch_size=feed_rows, seed=seed, shuffle=True,
        drop_remainder=True,
    )
    steps_per_epoch = loader.num_batches
    if steps_per_epoch == 0:
        loader.close()
        raise ValueError(
            f"elastic trainer needs at least one full batch: {loader.n_rows} "
            f"rows < accumulate_steps * batch_size = {feed_rows} (shapes "
            "must be static for the jitted step — lower batch_size)"
        )
    total_steps = steps_per_epoch * num_epochs

    # checkpoint I/O series belong in the same scrape as the goodput
    # buckets they feed (a tracker with a private registry would
    # otherwise watch unionml_checkpoint_save_ms accrue globally)
    manager = make_checkpoint_manager(
        checkpoint_dir, max_to_keep=max_to_keep, backend=checkpoint_backend,
        registry=tracker.registry if tracker is not None else None,
    )
    global_step = 0
    resume_step = manager.latest_step()
    if resume_step is not None:
        # resuming after a kill: the restore is preemption badput — the
        # measured price of the eviction, not of checkpointing policy
        with _phase(tracker, "preemption"):
            state = manager.restore(state, step=resume_step)
        global_step = resume_step
        logger.info(f"elastic trainer: resuming from step {global_step}")

    single = len(arrays) == 1
    try:
        start_epoch, start_batch = divmod(global_step, steps_per_epoch)

        def host_batches():
            for _epoch, _idx, batch in loader.epochs(
                num_epochs, start_epoch=start_epoch, start_batch=start_batch
            ):
                yield prepare(batch[0] if single else batch)

        with overlap_ctx:
            feed = prefetch_to_device(
                host_batches(), sharding=sharding, goodput=tracker,
                double_buffer=double_buffer,
            )
            with contextlib.closing(feed):
                for batch in feed:
                    t_step = time.perf_counter()
                    with _phase(tracker, "compute"):
                        state, _metrics = step(state, batch)
                    if tracker is not None:
                        tracker.step_complete(time.perf_counter() - t_step)
                    global_step += 1
                    if global_step % checkpoint_every == 0 or global_step == total_steps:
                        # async save: the device->host snapshot happens
                        # before save() returns (so donation of state
                        # buffers by the next step is safe); serialize +
                        # disk write + commit overlap the following steps
                        with _phase(tracker, "checkpoint"):
                            manager.save(global_step, state)
                    if fault_hook is not None:
                        fault_hook(global_step)
        # the trailing drain is device compute still in flight — it must
        # land in the compute bucket even in overlap mode (an overlapped
        # transfer the compute waited on is compute, not data_wait)
        import jax

        with _phase(tracker, "compute"):
            jax.block_until_ready(state)
    finally:
        loader.close()
        # a kill mid-commit leaves only an uncommitted tmp dir (atomic
        # rename); close() drains the background commit and releases the
        # writer thread — best-effort, so a checkpoint failure in the
        # drain never masks the exception that ended the loop
        with _phase(tracker, "checkpoint"):
            manager.close()
        if tracker is not None:
            tracker.finish()

    logger.info(f"elastic trainer: finished at step {global_step}/{total_steps}")
    return state, global_step


def _run_stream(
    step: Callable,
    state: Any,
    stream: Callable,
    *,
    checkpoint_dir: str,
    num_steps: Optional[int],
    checkpoint_every: int,
    max_to_keep: int,
    checkpoint_backend: str = "auto",
    fault_hook: Optional[Callable[[int], None]],
    tracker: Any = None,
    prepare: Callable[[Any], Any] = lambda batch: batch,
    sharding: Any = None,
    double_buffer: bool = False,
) -> Tuple[Any, int]:
    """Step-indexed resumable loop over a streaming batch source."""
    import inspect

    manager = make_checkpoint_manager(
        checkpoint_dir, max_to_keep=max_to_keep, backend=checkpoint_backend,
        registry=tracker.registry if tracker is not None else None,
    )
    global_step = 0
    resume_step = manager.latest_step()
    if resume_step is not None:
        with _phase(tracker, "preemption"):
            state = manager.restore(state, step=resume_step)
        global_step = resume_step
        logger.info(f"elastic trainer: resuming stream from step {global_step}")
    if num_steps is not None and global_step >= num_steps:
        manager.close()
        if tracker is not None:
            tracker.finish()
        return state, global_step

    params = inspect.signature(stream).parameters.values()
    required = [p for p in params if p.default is inspect.Parameter.empty
                and p.kind is not inspect.Parameter.VAR_KEYWORD
                and p.kind is not inspect.Parameter.VAR_POSITIONAL]
    if any(p.kind is inspect.Parameter.KEYWORD_ONLY for p in required):
        raise ValueError(
            "stream callables take the resume step as ONE positional "
            "argument (seekable form) or no required arguments (replayable "
            "form); a required keyword-only parameter fits neither — see "
            "run_elastic_trainer's streaming contract"
        )
    seekable = bool(required)
    if seekable:
        batches = stream(global_step)
        skip = 0
    else:
        batches = stream()
        skip = global_step
        if skip:
            logger.info(
                f"elastic trainer: replaying stream, skipping {skip} "
                "consumed batches (pass stream(start_step) to seek instead)"
            )
    trained = 0
    try:
        it = iter(batches)
        # eager replay skip: producing the already-consumed batches again
        # is preemption badput, not data starvation — and doing it BEFORE
        # the prefetch feed starts keeps skipped batches off the device
        while skip:
            with _phase(tracker, "preemption"):
                batch = next(it, _STREAM_END)
            if batch is _STREAM_END:
                # the replayed stream ended BEFORE the resume position:
                # returning "finished" would silently bless a truncated or
                # non-deterministic source
                raise RuntimeError(
                    f"stream exhausted {skip} batches before the resume "
                    f"position (step {global_step}): the replayed stream "
                    "must reproduce at least the batches already consumed"
                )
            skip -= 1

        def host_batches():
            for batch in it:
                yield prepare(batch)

        exhausted = True
        feed = prefetch_to_device(
            host_batches(), sharding=sharding, goodput=tracker,
            double_buffer=double_buffer,
        )
        with contextlib.closing(feed):
            for batch in feed:
                t_step = time.perf_counter()
                with _phase(tracker, "compute"):
                    state, _metrics = step(state, batch)
                if tracker is not None:
                    tracker.step_complete(time.perf_counter() - t_step)
                global_step += 1
                trained += 1
                at_bound = num_steps is not None and global_step >= num_steps
                if global_step % checkpoint_every == 0 or at_bound:
                    with _phase(tracker, "checkpoint"):
                        manager.save(global_step, state)
                if fault_hook is not None:
                    fault_hook(global_step)
                if at_bound:
                    exhausted = False
                    break
        import jax

        # trailing drain = in-flight device compute (see run_step_trainer)
        with _phase(tracker, "compute"):
            jax.block_until_ready(state)
        if exhausted:
            # stream exhausted: persist the terminal position so a restart
            # resumes AFTER the last consumed batch instead of re-training
            # — unless nothing ran since resume (the state is unchanged and
            # a terminal checkpoint for it already exists)
            if trained and global_step % checkpoint_every != 0:
                with _phase(tracker, "checkpoint"):
                    manager.save(global_step, state)
    finally:
        with _phase(tracker, "checkpoint"):
            manager.close()
        if tracker is not None:
            tracker.finish()

    logger.info(f"elastic trainer: stream finished at step {global_step}")
    return state, global_step
