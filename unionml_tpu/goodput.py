"""Training goodput accounting: step-phase attribution & badput causes.

The serving path is fully explainable (telemetry, MFU gauges, traces,
SLOs — PRs 1/4/5), but the training loop exposed only coarse
``step_ms``/``loss``/``samples_per_sec`` gauges: a flat samples/sec
number says *that* training is slow, never *why*. This module is the
training-side twin of the serving observability stack, in the
MegaScale / Google-Goodput lineage: classify every second of trainer
wall time into **compute** (the jitted step doing useful work) versus
named **badput** causes, so the bottleneck is measured, not guessed.

- :class:`GoodputTracker` — the accountant the trainer loops
  (:func:`unionml_tpu.execution.run_step_trainer`,
  :func:`unionml_tpu.elastic.run_elastic_trainer`) thread their phases
  through. Each :meth:`~GoodputTracker.phase` scope lands its wall
  time in one bucket (:data:`BADPUT_CAUSES`): ``data_wait`` (host
  input starvation in the stream feed), ``host_to_device`` (the
  ``DeviceFeed.put`` / ``prefetch_to_device`` dispatch),
  ``compile`` (XLA compile/recompile, detected by PR 4's
  :class:`~unionml_tpu.introspection.ProgramTracker` and *debited
  out of* the enclosing compute phase), ``checkpoint``
  (save/restore stall on the critical path), and ``preemption``
  (elastic restore + replay after a slice preemption). Published
  series: ``unionml_train_goodput_ratio``,
  ``unionml_train_goodput_seconds_total``,
  ``unionml_train_badput_seconds_total{cause}``, and the per-phase
  ``unionml_train_phase_ms{phase}`` histogram. Each phase is also a
  span on a per-run :class:`~unionml_tpu.telemetry.TraceRecorder`
  timeline, so trainer timelines export through the same Chrome-trace
  / OTLP path as serving requests.
- :class:`StepTimeRegressionDetector` — a rolling-baseline anomaly
  detector over per-step wall times with hysteresis: an anomaly fires
  after ``consecutive`` steps above ``threshold`` × the baseline
  median and clears after ``consecutive`` steps below
  ``clear_threshold`` ×. The live ratio publishes as
  ``unionml_train_step_time_ratio``, transitions count into
  ``unionml_train_step_anomalies_total`` and land in the flight
  recorder (``step_time_anomaly`` / ``step_time_regression`` events)
  — and a :class:`~unionml_tpu.slo.GaugeObjective` over the ratio (or
  over ``unionml_train_goodput_ratio``) lets the PR 5 SLO watchdog
  breach on goodput collapse.
- :class:`StepSkewMonitor` — per-host step-completion skew on the
  multihost path: gauges ``unionml_train_step_skew_ms`` /
  ``unionml_train_host_step_ms{process}``, plus ``straggler`` flight
  events (and ``unionml_train_stragglers_total``) naming the host
  whose step ran past ``straggler_factor`` × the median.
  :func:`allgather_step_times` is the one jax touchpoint (a
  ``process_allgather`` of this host's step time, skipped
  single-process); the monitor itself is pure math on injected
  timings, so the skew logic is unit-testable without a slice.

Everything here is stdlib-only (jax is imported only inside
:func:`allgather_step_times`), thread-safe, and takes an injectable
monotonic ``clock`` so the bucket math is testable on a synthetic
clock. Instrumentation cost per phase is two clock reads, one lock
acquisition, and counter increments — the ``train_goodput`` bench
preset (``benchmarks/train_throughput.py``) holds the measured
overhead under 2% while requiring the buckets to explain ≥95% of wall
time on a fault-injected run.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from unionml_tpu import telemetry
from unionml_tpu._logging import logger

__all__ = [
    "BADPUT_CAUSES",
    "COMPUTE_PHASE",
    "GoodputTracker",
    "StepSkewMonitor",
    "StepTimeRegressionDetector",
    "allgather_step_times",
    "phase_scope",
]


def phase_scope(tracker: Optional["GoodputTracker"], name: str):
    """Phase scope on ``tracker``, or a no-op when accounting is off —
    the one phase-or-noop helper the trainer loops share, so optional
    instrumentation never re-invents the ``if tracker`` branch at every
    call site."""
    if tracker is None:
        return contextlib.nullcontext()
    return tracker.phase(name)

#: The one good phase: wall time inside the jitted step (minus any
#: compile debit) counts toward goodput.
COMPUTE_PHASE = "compute"

#: The badput taxonomy (docs/observability.md "Training goodput").
#: Any phase name outside COMPUTE_PHASE + BADPUT_CAUSES is rejected —
#: an unknown bucket would silently leak out of the attribution sum.
BADPUT_CAUSES = (
    "data_wait",        # host input starvation (the stream/loader feed)
    "host_to_device",   # DeviceFeed.put / prefetch_to_device dispatch
    "compile",          # XLA compile/recompile (ProgramTracker events)
    "checkpoint",       # checkpoint save/restore stall on the loop
    "preemption",       # elastic restore + replay after preemption
)


class StepTimeRegressionDetector:
    """Rolling-baseline step-time anomaly detection with hysteresis.

    The baseline is the median of the newest ``window`` *normal* step
    durations (anomalous steps never feed it, so a sustained
    regression cannot absorb itself into the baseline). A step is
    *anomalous* when its duration exceeds ``threshold`` × baseline;
    the detector enters the **regressed** state after ``consecutive``
    anomalous steps in a row and leaves it only after ``consecutive``
    steps below ``clear_threshold`` × baseline — the two thresholds
    are the hysteresis band that keeps a step time oscillating around
    the trip point from flapping the state. The first ``min_steps``
    steps only warm the baseline (never anomalous).

    Pure math — no clocks, no registries — so the hysteresis is
    unit-testable from a list of synthetic durations.
    """

    def __init__(
        self,
        *,
        window: int = 50,
        threshold: float = 1.5,
        clear_threshold: float = 1.2,
        consecutive: int = 3,
        min_steps: int = 10,
    ):
        if threshold <= clear_threshold:
            raise ValueError(
                f"threshold ({threshold}) must exceed clear_threshold "
                f"({clear_threshold}) — equal bands have no hysteresis"
            )
        if window < 2 or consecutive < 1 or min_steps < 1:
            raise ValueError("window >= 2, consecutive >= 1, min_steps >= 1")
        self.window = int(window)
        self.threshold = float(threshold)
        self.clear_threshold = float(clear_threshold)
        self.consecutive = int(consecutive)
        self.min_steps = int(min_steps)
        self._normal: List[float] = []
        self._steps = 0
        self._over = 0
        self._under = 0
        self.regressed = False
        self.anomalies = 0

    def baseline(self) -> Optional[float]:
        """Median of the retained normal durations (None while the
        warmup window is still filling)."""
        if self._steps < self.min_steps or not self._normal:
            return None
        vals = sorted(self._normal)
        return vals[len(vals) // 2]

    def update(self, step_s: float) -> dict:
        """Feed one step duration; returns ``{"ratio", "anomaly",
        "regressed", "entered", "cleared"}`` — ``entered``/``cleared``
        flag the regressed-state *transitions* this update caused."""
        step_s = float(step_s)
        self._steps += 1
        base = self.baseline()
        ratio = (step_s / base) if base else 1.0
        anomaly = base is not None and ratio > self.threshold
        entered = cleared = False
        if anomaly:
            self.anomalies += 1
            self._over += 1
            self._under = 0
            if not self.regressed and self._over >= self.consecutive:
                self.regressed = True
                entered = True
        else:
            self._over = 0
            self._normal.append(step_s)
            if len(self._normal) > self.window:
                del self._normal[: -self.window]
            if self.regressed:
                if base is None or ratio < self.clear_threshold:
                    self._under += 1
                    if self._under >= self.consecutive:
                        self.regressed = False
                        cleared = True
                        self._under = 0
                else:
                    self._under = 0
        return {
            "ratio": ratio,
            "anomaly": anomaly,
            "regressed": self.regressed,
            "entered": entered,
            "cleared": cleared,
        }


class StepSkewMonitor:
    """Per-host step-completion skew + straggler detection (pure math).

    ``observe(step, host_step_s)`` takes every host's step duration
    for one synchronization point (what :func:`allgather_step_times`
    returns on a slice, or a synthetic list in tests) and reports the
    skew — slowest minus median, the time every other host spent
    waiting at the collective — and which hosts ran past
    ``straggler_factor`` × the median AND ``min_skew_ms`` absolute
    margin (the absolute floor keeps µs-scale jitter on a fast step
    from flagging phantom stragglers).
    """

    def __init__(
        self, *, straggler_factor: float = 1.5, min_skew_ms: float = 50.0
    ):
        if straggler_factor <= 1.0:
            raise ValueError("straggler_factor must be > 1.0")
        self.straggler_factor = float(straggler_factor)
        self.min_skew_ms = float(min_skew_ms)

    def observe(self, step: int, host_step_s: Sequence[float]) -> dict:
        times = [float(t) for t in host_step_s]
        if not times:
            raise ValueError("host_step_s must be non-empty")
        ordered = sorted(times)
        # LOWER middle element for even host counts: the upper middle
        # would make a 2-host slice blind (median == slowest ⇒ skew 0
        # and the straggler ratio can never trip); the lower middle
        # keeps "how long did the rest of the slice wait" meaningful
        # down to 2 processes
        median = ordered[(len(ordered) - 1) // 2]
        slowest = max(times)
        skew_ms = (slowest - median) * 1e3
        stragglers = [
            host for host, t in enumerate(times)
            if t > median * self.straggler_factor
            and (t - median) * 1e3 >= self.min_skew_ms
        ]
        return {
            "step": int(step),
            "median_ms": median * 1e3,
            "slowest_ms": slowest * 1e3,
            "skew_ms": skew_ms,
            "stragglers": stragglers,
        }


def allgather_step_times(step_s: float) -> Optional[List[float]]:
    """Every process's ``step_s``, index-aligned with
    ``jax.process_index()`` — the multihost sync point feeding
    :class:`StepSkewMonitor`. Returns ``None`` single-process (no
    collective, no cost) or when the gather fails (a skew sample must
    never take training down)."""
    import jax

    if jax.process_count() <= 1:
        return None
    try:
        import numpy as np
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(
            np.asarray(step_s, dtype=np.float64)
        )
        return [float(t) for t in np.asarray(gathered).reshape(-1)]
    except Exception as exc:
        logger.info(f"step-skew allgather unavailable: {exc!r}")
        return None


class _PhaseScope:
    def __init__(self, tracker: "GoodputTracker", name: str):
        self._tracker = tracker
        self._name = name
        self._t0 = 0.0

    def __enter__(self) -> "_PhaseScope":
        self._t0 = self._tracker._clock()
        return self

    def __exit__(self, *exc) -> None:
        self._tracker._end_phase(self._name, self._t0, self._tracker._clock())


class GoodputTracker:
    """Decomposes trainer wall time into compute vs. badput buckets.

    The trainer loops open :meth:`phase` scopes around every
    classifiable stretch of wall time; :meth:`report` divides the
    accumulated buckets by the :meth:`start` → now wall span. Compile
    time discovered *inside* a compute phase (the
    :class:`~unionml_tpu.introspection.ProgramTracker` ``on_compile``
    hook calls :meth:`note_compile_ms`) is debited out of that compute
    phase into the ``compile`` bucket, so goodput never counts an XLA
    recompile as useful work and the buckets still sum to measured
    wall time.

    ``registry`` / ``tracer`` / ``flight`` default to the
    process-global telemetry instances (one scrape covers serving and
    training); ``clock`` (monotonic seconds) is injectable for
    deterministic tests. All methods are thread-safe — the prefetch
    feed and the step loop may run phases from different threads.
    """

    def __init__(
        self,
        *,
        registry: Optional[telemetry.MetricsRegistry] = None,
        tracer: Optional[telemetry.TraceRecorder] = None,
        flight: Optional[telemetry.FlightRecorder] = None,
        clock: Callable[[], float] = time.perf_counter,
        detector: Optional[StepTimeRegressionDetector] = None,
        skew_monitor: Optional[StepSkewMonitor] = None,
        timeline_rotate_steps: int = 512,
    ):
        self._registry = (
            registry if registry is not None else telemetry.get_registry()
        )
        self._tracer = tracer if tracer is not None else telemetry.get_tracer()
        self._flight = (
            flight if flight is not None else telemetry.get_flight_recorder()
        )
        self._clock = clock
        self.detector = (
            detector if detector is not None else StepTimeRegressionDetector()
        )
        self.skew_monitor = (
            skew_monitor if skew_monitor is not None else StepSkewMonitor()
        )
        # long runs record 3-4 phase spans per step against the trace
        # recorder's per-request span cap: rotate the trainer timeline
        # onto a fresh request every N steps (512 * 4 spans stays well
        # under MAX_SPANS_PER_REQUEST=4096) so a 100k-step run exports
        # its whole history as a chain of requests instead of silently
        # truncating after the first ~1k steps. 0 disables rotation.
        self._timeline_rotate_steps = int(timeline_rotate_steps)
        self._lock = threading.Lock()
        self._buckets: Dict[str, float] = {COMPUTE_PHASE: 0.0}
        for cause in BADPUT_CAUSES:
            self._buckets[cause] = 0.0
        self._pending_compile_s = 0.0
        self._t_start: Optional[float] = None
        self._t_stop: Optional[float] = None
        self._steps = 0
        self._rid: Optional[str] = None
        R = self._registry
        self._g_ratio = R.gauge(
            "unionml_train_goodput_ratio",
            "Compute seconds over trainer wall seconds since start() "
            "(1.0 = every second was jitted compute).",
        )
        self._c_good = R.counter(
            "unionml_train_goodput_seconds_total",
            "Trainer wall seconds classified as jitted compute.",
        )
        self._c_bad = R.counter(
            "unionml_train_badput_seconds_total",
            "Trainer wall seconds lost to a named badput cause.",
            ("cause",),
        )
        self._h_phase = R.histogram(
            "unionml_train_phase_ms",
            "Per-occurrence wall time of one trainer phase.",
            ("phase",),
        )
        # hot-path children resolved once: _end_phase runs up to four
        # times per training step and must not pay the family-lock
        # labels() lookup each time
        self._bad_children = {
            cause: self._c_bad.labels(cause) for cause in BADPUT_CAUSES
        }
        self._phase_children = {
            name: self._h_phase.labels(name)
            for name in (COMPUTE_PHASE,) + BADPUT_CAUSES
        }
        self._g_ratio_step = R.gauge(
            "unionml_train_step_time_ratio",
            "Current step time over the rolling-baseline median "
            "(regression detector; 1.0 = at baseline).",
        )
        self._c_anomalies = R.counter(
            "unionml_train_step_anomalies_total",
            "Steps whose wall time exceeded the regression detector's "
            "anomaly threshold.",
        )
        self._g_skew = R.gauge(
            "unionml_train_step_skew_ms",
            "Slowest-host minus median-host step time at the last "
            "multihost skew sample.",
        )
        self._g_host_step = R.gauge(
            "unionml_train_host_step_ms",
            "Per-host step wall time at the last multihost skew sample.",
            ("process",),
        )
        self._c_stragglers = R.counter(
            "unionml_train_stragglers_total",
            "Hosts observed past straggler_factor x the median step "
            "time at a skew sample.",
        )

    @property
    def registry(self) -> telemetry.MetricsRegistry:
        """The registry this tracker publishes into — the trainer loops
        use it so companion instrumentation (the program tracker's
        compile series) lands in the same scrape."""
        return self._registry

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Anchor the wall clock and open the per-run trace timeline.
        Idempotent while running — the trainer calls it
        unconditionally, a caller that pre-started the tracker loses
        nothing. Calling it again after :meth:`finish` RESUMES the
        accounting: the paused gap is excluded from wall time (the
        buckets keep accumulating), so one tracker can span several
        trainer invocations and still report an honest attribution."""
        reopen = False
        with self._lock:
            now = self._clock()
            if self._t_start is None:
                self._t_start = now
                reopen = True
            elif self._t_stop is not None:
                self._t_start += now - self._t_stop
                self._t_stop = None
                reopen = True
        if reopen:
            self._rid = self._tracer.new_request(kind="trainer")

    def finish(self) -> None:
        """Freeze the wall span and finish the trace timeline (the
        spans export through ``/debug/trace`` and OTLP like any
        serving request). :meth:`report` stays readable after."""
        with self._lock:
            if self._t_start is None or self._t_stop is not None:
                return
            self._t_stop = self._clock()
            rid = self._rid
        if rid is not None:
            self._tracer.finish_request(rid)
        self._publish_ratio()

    # -- phases ------------------------------------------------------------

    def phase(self, name: str) -> _PhaseScope:
        """Context manager attributing its wall time to bucket
        ``name`` (``compute`` or one of :data:`BADPUT_CAUSES`)."""
        if name != COMPUTE_PHASE and name not in BADPUT_CAUSES:
            raise ValueError(
                f"unknown phase {name!r}: expected {COMPUTE_PHASE!r} or "
                f"one of {BADPUT_CAUSES}"
            )
        return _PhaseScope(self, name)

    def note_compile_ms(self, key: str, dt_ms: float) -> None:
        """ProgramTracker ``on_compile`` hook: ``dt_ms`` of the call
        that just compiled becomes a pending debit, moved from the
        enclosing compute phase into the ``compile`` bucket when that
        phase closes."""
        with self._lock:
            self._pending_compile_s += max(0.0, float(dt_ms)) / 1e3
        self._flight.record(
            "train_compile", program=key, compile_ms=round(float(dt_ms), 3)
        )

    def _end_phase(self, name: str, t0: float, t1: float) -> None:
        dt = max(0.0, t1 - t0)
        compile_debit = 0.0
        with self._lock:
            if name == COMPUTE_PHASE and self._pending_compile_s > 0.0:
                compile_debit = min(self._pending_compile_s, dt)
                self._pending_compile_s -= compile_debit
            self._buckets[name] += dt - compile_debit
            if compile_debit:
                self._buckets["compile"] += compile_debit
            steps = self._steps
            rid = self._rid
        self._phase_children[name].observe(dt * 1e3)
        if name == COMPUTE_PHASE:
            if dt - compile_debit:
                self._c_good.inc(dt - compile_debit)
        else:
            self._bad_children[name].inc(dt)
        if compile_debit:
            self._bad_children["compile"].inc(compile_debit)
        if rid is not None:
            self._tracer.record_span(rid, name, t0, t1, step=steps)

    # -- per-step hooks ----------------------------------------------------

    def step_complete(self, step_s: float, *, detect: bool = True) -> dict:
        """Called once per trainer step with its wall duration; feeds
        the regression detector, publishes the ratio gauge, counts
        anomalies, and records regression transitions in the flight
        recorder. Returns the detector verdict.

        ``detect=False`` counts the step (and rotates the timeline)
        but keeps the sample OUT of the regression detector — for
        steps whose timing is known to be non-comparable to the rest,
        e.g. the async-dispatch trainer's window-boundary steps whose
        forced readback drains a whole window of device work into one
        sample (every boundary would read as a >1.5x anomaly against a
        dispatch-scale baseline)."""
        rotate_rid = None
        with self._lock:
            self._steps += 1
            step = self._steps
            if (
                self._timeline_rotate_steps > 0
                and self._rid is not None
                and self._t_stop is None
                and step % self._timeline_rotate_steps == 0
            ):
                rotate_rid = self._rid
            if detect:
                # the detector mutates its baseline window unsynchronized
                # — updating it under the tracker lock keeps the
                # documented thread-safety claim true for concurrent
                # step_complete calls
                verdict = self.detector.update(step_s)
            else:
                verdict = {
                    "ratio": 1.0, "anomaly": False,
                    "regressed": self.detector.regressed,
                    "entered": False, "cleared": False,
                }
        if rotate_rid is not None:
            self._tracer.finish_request(rotate_rid)
            new_rid = self._tracer.new_request(kind="trainer")
            with self._lock:
                self._rid = new_rid
        # the ratio gauge refreshes once per step, not on every phase
        # close — the gauge readers (scrapes, the SLO watchdog) sample
        # far slower than the loop's 3-4 phases per step
        self._publish_ratio()
        if detect:
            self._g_ratio_step.set(verdict["ratio"])
        if verdict["anomaly"]:
            self._c_anomalies.inc()
            self._flight.record(
                "step_time_anomaly",
                step=step,
                step_ms=round(step_s * 1e3, 3),
                ratio=round(verdict["ratio"], 3),
            )
        if verdict["entered"] or verdict["cleared"]:
            self._flight.record(
                "step_time_regression",
                step=step,
                state="entered" if verdict["entered"] else "cleared",
                ratio=round(verdict["ratio"], 3),
            )
        return verdict

    def record_step_skew(
        self, step: int, host_step_s: Sequence[float]
    ) -> dict:
        """Publish one multihost skew sample (see
        :class:`StepSkewMonitor`); straggler hosts land in the flight
        recorder so a post-hoc reader can name the slow host."""
        sample = self.skew_monitor.observe(step, host_step_s)
        self._g_skew.set(sample["skew_ms"])
        for host, t in enumerate(host_step_s):
            self._g_host_step.labels(str(host)).set(float(t) * 1e3)
        for host in sample["stragglers"]:
            self._c_stragglers.inc()
            self._flight.record(
                "straggler",
                step=sample["step"],
                process=host,
                host_step_ms=round(float(host_step_s[host]) * 1e3, 3),
                median_ms=round(sample["median_ms"], 3),
            )
        return sample

    # -- reporting ---------------------------------------------------------

    def _wall_s(self) -> float:
        if self._t_start is None:
            return 0.0
        end = self._t_stop if self._t_stop is not None else self._clock()
        return max(0.0, end - self._t_start)

    def _publish_ratio(self) -> None:
        with self._lock:
            wall = self._wall_s()
            compute = self._buckets[COMPUTE_PHASE]
        if wall > 0.0:
            self._g_ratio.set(min(1.0, compute / wall))

    def report(self) -> dict:
        """The attribution summary the bench preset and tests assert
        on: per-bucket seconds, wall seconds since :meth:`start`,
        ``goodput_ratio`` (compute/wall), ``attributed_fraction``
        (all buckets / wall — the ≥95% acceptance bar), and
        ``unattributed_s`` (loop bookkeeping between phases)."""
        with self._lock:
            wall = self._wall_s()
            buckets = dict(self._buckets)
            steps = self._steps
        attributed = sum(buckets.values())
        return {
            "wall_s": wall,
            "steps": steps,
            "buckets_s": {k: round(v, 6) for k, v in buckets.items()},
            "goodput_s": round(buckets[COMPUTE_PHASE], 6),
            "badput_s": {
                cause: round(buckets[cause], 6) for cause in BADPUT_CAUSES
            },
            "goodput_ratio": (
                round(buckets[COMPUTE_PHASE] / wall, 6) if wall else 0.0
            ),
            "attributed_fraction": (
                round(min(1.0, attributed / wall), 6) if wall else 0.0
            ),
            "unattributed_s": round(max(0.0, wall - attributed), 6),
            "anomalies": self.detector.anomalies,
            "regressed": self.detector.regressed,
        }
