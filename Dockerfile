# App image for remote deployment (reference analog: the root Dockerfile
# template users build FROM). On TPU VMs, install the TPU jax wheel at
# build time; the default target is CPU so the image also works as the
# sandbox/CI base.
FROM python:3.12-slim

# g++ for the native host batch loader (compiled on first use)
RUN apt-get update && apt-get install -y --no-install-recommends \
    g++ git && rm -rf /var/lib/apt/lists/*

WORKDIR /app

ARG JAX_VARIANT=""
# dependency layer first so source edits don't re-download wheels
# TPU VMs: --build-arg JAX_VARIANT="[tpu]" (pulls libtpu)
COPY pyproject.toml README.md /app/
RUN pip install --no-cache-dir "jax${JAX_VARIANT}" pandas scikit-learn fastapi \
    flax optax orbax-checkpoint click numpy

COPY . /app
RUN pip install --no-cache-dir --no-deps -e .

EXPOSE 8000
ENTRYPOINT ["unionml-tpu"]
CMD ["--help"]
