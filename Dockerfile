# App image for remote deployment (reference analog: the root Dockerfile
# template users build FROM). On TPU VMs, install the TPU jax wheel at
# build time; the default target is CPU so the image also works as the
# sandbox/CI base.
FROM python:3.12-slim

# g++ for the native host batch loader (compiled on first use)
RUN apt-get update && apt-get install -y --no-install-recommends \
    g++ git && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY . /app

ARG JAX_VARIANT=""
# TPU VMs: --build-arg JAX_VARIANT="[tpu]" (pulls libtpu). Dependencies
# come from pyproject extras so the image can never drift from the
# package metadata (a hand-maintained list here silently dropped uvicorn
# once — correctness beats layer caching).
RUN pip install --no-cache-dir "jax${JAX_VARIANT}" && \
    pip install --no-cache-dir -e ".[tabular,fastapi]"

EXPOSE 8000
ENTRYPOINT ["unionml-tpu"]
CMD ["--help"]
