"""Training-throughput benchmarks beyond the headline ViT (bench.py).

Reproduces the remaining BASELINE.md training rows on one chip:

- ``bert_ft``  — BERT-base classification fine-tune (batch 32, seq 128),
  samples/sec/chip; the config that exposed the donated-optax-adamw
  pathology (BASELINE.md) — uses the donation-safe ``adamw`` chain.
- ``llama_lc`` — long-context LM training (0.19B-param Llama geometry,
  batch 2, seq 4096, Pallas flash attention), tokens/sec/chip.

Prints one JSON line per config. Timing follows the BASELINE.md
methodology: warmup, >=100-step window on TPU, end with a host readback
data-dependent on the final donated state.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def _time_steps(step, state, batch, steps, warmup):
    from benchmarks._timing import drain

    for _ in range(warmup):
        state, metrics = step(state, batch)
    drain(state)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch)
    drain(state)
    return time.perf_counter() - t0


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.models import (
        BertClassifier,
        BertConfig,
        Llama,
        LlamaConfig,
        classification_step,
        create_train_state,
        lm_step,
    )

    tiny = os.environ.get("UNIONML_TPU_BENCH_PRESET") == "tiny" or (
        jax.default_backend() == "cpu"
    )
    steps, warmup = (3, 1) if tiny else (100, 10)
    rng = np.random.default_rng(0)

    # -- BERT-base fine-tune ------------------------------------------- #
    bcfg = BertConfig.tiny() if tiny else BertConfig.base(num_classes=2)
    batch, seq = (4, 16) if tiny else (32, 128)
    bert = BertClassifier(bcfg)
    ids = jnp.asarray(rng.integers(0, bcfg.vocab_size, size=(batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, 2, size=(batch,)), jnp.int32)
    state = create_train_state(bert, ids[:1], learning_rate=2e-5)
    step = jax.jit(classification_step(bert), donate_argnums=0)
    dt = _time_steps(step, state, (ids, labels), steps, warmup)
    print(json.dumps({
        "metric": "bert_ft_train_samples_per_sec_per_chip",
        "batch": batch, "seq": seq,
        "value": round(batch * steps / dt, 1),
        "unit": "samples/sec/chip",
    }))

    # -- long-context Llama LM ----------------------------------------- #
    if tiny:
        lcfg = LlamaConfig.tiny(vocab_size=256)
        batch, seq = 2, 64
    else:
        # ~0.19B params: 12 x 768 Llama geometry, flash attention
        lcfg = LlamaConfig(
            vocab_size=32_000, hidden_dim=768, num_layers=12, num_heads=12,
            num_kv_heads=4, mlp_dim=2048, max_len=4096, attn_impl="flash",
        )
        batch, seq = 2, 4096
    lm = Llama(lcfg)
    tokens = jnp.asarray(rng.integers(0, lcfg.vocab_size, size=(batch, seq)), jnp.int32)
    state = create_train_state(lm, tokens[:1, :8], learning_rate=1e-3)
    step = jax.jit(lm_step(lm), donate_argnums=0)
    dt = _time_steps(step, state, tokens, steps, warmup)
    print(json.dumps({
        "metric": "llama_lc_train_tokens_per_sec_per_chip",
        "batch": batch, "seq": seq,
        "value": round(batch * (seq - 1) * steps / dt, 1),
        "unit": "tokens/sec/chip",
    }))

    # -- QLoRA fine-tune (UNIONML_TPU_BENCH_PRESET=qlora_8b) ------------ #
    # The serving flagship run in reverse: fine-tune Llama-3-8B on ONE
    # chip. Full fine-tuning cannot fit (bf16 params + fp32 master + adam
    # m/v ~ 96 GB); QLoRA does: the int8 base (~8.6 GB, the same tree the
    # serving path streams) is frozen, and only rank-16 adapters (~42M
    # params, ~0.5 GB with adam state) train. Per-block remat keeps
    # activations at one block.
    if os.environ.get("UNIONML_TPU_BENCH_PRESET") == "qlora_8b" or tiny:
        from benchmarks.serve_latency import random_quantized_params

        from unionml_tpu.models import create_lora_train_state

        if tiny:
            qcfg = LlamaConfig.tiny(vocab_size=256, quantized=True)
            batch, seq, rank = 2, 32, 4
        else:
            qcfg = LlamaConfig(
                quantized=True, remat=True, attn_impl="flash", max_len=2048
            )
            batch, seq, rank = 1, 1024, 16
        base = random_quantized_params(Llama(qcfg))
        import dataclasses

        lcfg = dataclasses.replace(qcfg, lora_rank=rank)
        lora_llama = Llama(lcfg)
        state = create_lora_train_state(
            lora_llama, jnp.zeros((1, 8), jnp.int32), base_params=base,
            learning_rate=1e-4,
        )
        del base  # the state holds the only reference now
        tokens = jnp.asarray(
            rng.integers(0, qcfg.vocab_size, size=(batch, seq)), jnp.int32
        )
        step = jax.jit(lm_step(lora_llama), donate_argnums=0)
        n_steps = steps if tiny else 30  # ~0.5 s/step at 8B: 30 suffice
        dt = _time_steps(step, state, tokens, n_steps, warmup if tiny else 5)
        print(json.dumps({
            "metric": "qlora_8b_train_tokens_per_sec_per_chip",
            "batch": batch, "seq": seq, "lora_rank": rank,
            "value": round(batch * (seq - 1) * n_steps / dt, 1),
            "unit": "tokens/sec/chip",
        }))

    # -- long-context scaling (UNIONML_TPU_BENCH_LC_SCALE=1) ------------ #
    # tokens/sec vs sequence length at a constant 8192-token batch:
    # flash attention keeps memory linear in seq; per-block remat trades
    # recompute for activation memory at 16k+
    if os.environ.get("UNIONML_TPU_BENCH_LC_SCALE") and not tiny:
        for b, s, remat, accum in (
            (1, 8192, False, 1),
            (1, 16384, True, 1),
            # HBM caps the 16k config at microbatch 1; gradient
            # accumulation restores an effective batch of 4 with the
            # same activation footprint — the accumulate_steps knob's
            # long-context cost is this row vs the one above
            (1, 16384, True, 4),
        ):
            scfg = LlamaConfig(**{**lcfg.__dict__, "max_len": s, "remat": remat})
            lm_s = Llama(scfg)
            toks = jnp.asarray(
                rng.integers(0, scfg.vocab_size, size=(b * accum, s)), jnp.int32
            )
            if accum > 1:
                toks = toks.reshape(accum, b, s)
            st = create_train_state(
                lm_s, jnp.zeros((1, 8), jnp.int32), learning_rate=1e-3
            )
            stp = jax.jit(lm_step(lm_s, accumulate_steps=accum), donate_argnums=0)
            n_steps = max(20, steps // 4)  # longer steps: fewer suffice
            dt = _time_steps(stp, st, toks, n_steps, max(2, warmup // 2))
            print(json.dumps({
                "metric": "llama_lc_scale_tokens_per_sec_per_chip",
                "batch": b, "seq": s, "remat": remat,
                "accumulate_steps": accum,
                "value": round(b * accum * (s - 1) * n_steps / dt, 1),
                "unit": "tokens/sec/chip",
            }))


def goodput_leg() -> None:
    """``UNIONML_TPU_BENCH_PRESET=train_goodput``: goodput attribution
    on a fault-injected training loop (docs/observability.md
    "Training goodput").

    Three measurements, asserted not just recorded:

    1. **Attribution** — an elastic-trainer run with a forced data
       stall (the stream sleeps), synchronous checkpoints on the loop,
       and an induced recompile (one odd-shaped batch mid-stream) must
       have its compute + badput buckets explain >= 95% of wall time,
       with each injected fault visible in its named bucket.
    2. **Overhead** — the same in-memory streaming loop with goodput
       instrumentation off vs. on (min of 3 interleaved trials each,
       pre-warmed jit cache) must differ by <= 2%.
    3. **SLO coupling** — a `GaugeObjective` on
       ``unionml_train_goodput_ratio`` flips the PR 5 watchdog to
       breached at the first evaluation after an induced goodput
       collapse (deterministic ``evaluate(now=)`` clock).
    """
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax
    from flax import linen as nn
    from flax.training import train_state

    from unionml_tpu.elastic import run_elastic_trainer
    from unionml_tpu.execution import run_step_trainer
    from unionml_tpu.goodput import GoodputTracker
    from unionml_tpu.slo import GaugeObjective, SloWatchdog
    from unionml_tpu.telemetry import (
        FlightRecorder, MetricsRegistry, TraceRecorder,
    )

    class _Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(32)(x)
            x = nn.relu(x)
            return nn.Dense(2)(x)

    net = _Net()

    def make_state():
        params = net.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]
        return train_state.TrainState.create(
            apply_fn=net.apply, params=params, tx=optax.adam(1e-3)
        )

    def make_step():
        # a FRESH function object per call: _jitted caches per function,
        # so the attribution run gets a real cold compile while the
        # overhead legs share one warmed cache
        def step(state, batch):
            x, y = batch

            def loss_fn(p):
                logits = state.apply_fn({"params": p}, x)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, y
                ).mean()

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            return state.apply_gradients(grads=grads), {"loss": loss}

        return step

    rng = np.random.default_rng(0)

    def batch(rows):
        x = rng.normal(size=(rows, 8)).astype(np.float32)
        return x, (x[:, 0] > 0).astype(np.int32)

    n_steps, stall_steps, stall_s = 60, range(20, 25), 0.025
    batches = [batch(16) for _ in range(n_steps)]
    odd_batch = batch(24)  # one stray shape: the induced recompile

    def faulted_stream():
        for i in range(n_steps):
            if i in stall_steps:
                time.sleep(stall_s)  # forced data stall (host starvation)
            yield odd_batch if i == 40 else batches[i]

    # ---- 1. attribution on the fault-injected elastic run ------------- #
    import tempfile

    reg = MetricsRegistry()
    tracker = GoodputTracker(
        registry=reg, tracer=TraceRecorder(registry=reg),
        flight=FlightRecorder(),
    )
    run_elastic_trainer(
        step_fn=make_step(), state=make_state(), stream=faulted_stream,
        checkpoint_dir=tempfile.mkdtemp(prefix="train-goodput-"),
        checkpoint_every=10, goodput=tracker,
        # this leg asserts attribution with checkpoint stalls ON the
        # loop (see docstring); the async backend's identity is what
        # the train_overlap leg asserts
        checkpoint_backend="sync",
    )
    rep = tracker.report()
    bad = rep["badput_s"]
    assert rep["attributed_fraction"] >= 0.95, (
        f"attribution explains only {rep['attributed_fraction']:.1%} of "
        f"wall time (bar: 95%): {rep}"
    )
    injected_stall = len(stall_steps) * stall_s
    assert bad["data_wait"] >= injected_stall * 0.8, (
        f"injected {injected_stall}s data stall, data_wait bucket saw "
        f"only {bad['data_wait']}s"
    )
    assert bad["compile"] > 0, f"induced recompile not attributed: {bad}"
    assert bad["checkpoint"] > 0, f"checkpoint stall not attributed: {bad}"
    print(json.dumps({
        "metric": "train_goodput_attributed_fraction",
        "steps": rep["steps"],
        "value": rep["attributed_fraction"],
        "goodput_ratio": rep["goodput_ratio"],
        "badput_s": bad,
        "unit": "fraction",
    }))

    # ---- 2. instrumentation overhead on the in-memory loop ------------ #
    step = make_step()  # ONE function: both legs share the jit cache
    state0 = make_state()  # shared, donate_state=False below: reusing
    # one committed state keeps jit re-traces out of both legs — on a
    # shared CPU the per-run retrace jitters far more than the 2% bar

    paced_steps, pace_s = 100, 0.008

    def spin(seconds):
        # deterministic pacing floor: a sleep() here couples the
        # comparison to kernel timer quantization (measured: the extra
        # instrumentation syscalls shift sleep wakeups by far more than
        # the instrumentation itself costs); a spin burns exactly the
        # budget regardless of what ran between paces
        t_end = time.perf_counter() + seconds
        while time.perf_counter() < t_end:
            pass

    def stream_paced():
        # every step paced like a loader-fed loop, giving the percentage
        # comparison a deterministic wall floor
        for i in range(paced_steps):
            spin(pace_s)
            yield batches[i % n_steps]

    def run_once(goodput):
        t0 = time.perf_counter()
        run_step_trainer(
            step_fn=step, state=state0, features=stream_paced,
            registry=MetricsRegistry(), goodput=goodput,
            donate_state=False,
        )
        return time.perf_counter() - t0

    run_once(None)  # warm the jit cache out of both legs
    walls = {"off": [], "on": []}
    for _ in range(4):  # interleaved: drift hits both legs alike
        walls["off"].append(run_once(None))
        walls["on"].append(run_once(
            GoodputTracker(
                registry=MetricsRegistry(),
                tracer=TraceRecorder(registry=MetricsRegistry()),
                flight=FlightRecorder(),
            )
        ))
    t_off, t_on = min(walls["off"]), min(walls["on"])
    overhead_pct = (t_on - t_off) / t_off * 100.0
    assert overhead_pct <= 2.0, (
        f"goodput instrumentation overhead {overhead_pct:.2f}% exceeds "
        f"the 2% bar (off {t_off * 1e3:.1f} ms, on {t_on * 1e3:.1f} ms)"
    )
    print(json.dumps({
        "metric": "train_goodput_overhead_pct",
        "off_ms": round(t_off * 1e3, 1),
        "on_ms": round(t_on * 1e3, 1),
        "value": round(overhead_pct, 3),
        "unit": "%",
    }))

    # ---- 3. goodput collapse breaches the SLO watchdog ---------------- #
    reg = MetricsRegistry()
    tracker = GoodputTracker(
        registry=reg, tracer=TraceRecorder(registry=reg),
        flight=FlightRecorder(),
    )
    watchdog = SloWatchdog(
        [GaugeObjective(
            "train_goodput", "unionml_train_goodput_ratio", min_value=0.3,
        )],
        registry=reg, fast_window_s=5.0, slow_window_s=5.0,
    )

    # a heavier step for this leg: with measure_device_time every step
    # syncs, so real compute honestly dominates the healthy run's wall
    # time and the ratio is workload-determined, not scheduler noise
    class _Wide(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(256)(x)
            x = nn.relu(x)
            return nn.Dense(2)(x)

    wide = _Wide()
    wparams = wide.init(jax.random.PRNGKey(0), jnp.zeros((1, 32)))["params"]
    wstate = train_state.TrainState.create(
        apply_fn=wide.apply, params=wparams, tx=optax.adam(1e-3)
    )

    def wide_step(state, batch):
        x, y = batch

        def loss_fn(p):
            logits = state.apply_fn({"params": p}, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), {"loss": loss}

    wx = rng.normal(size=(64, 32)).astype(np.float32)
    wbatch = (wx, (wx[:, 0] > 0).astype(np.int32))

    def wide_stream(steps, stall=0.0):
        def it():
            for _ in range(steps):
                if stall:
                    time.sleep(stall)  # goodput collapse: starvation
                yield wbatch

        return it

    # warm the wide step's jit cache OUTSIDE the tracked runs, so no
    # compile debit muddies the healthy ratio
    run_step_trainer(
        step_fn=wide_step, state=wstate, features=wide_stream(3),
        registry=MetricsRegistry(), donate_state=False,
    )
    run_step_trainer(
        step_fn=wide_step, state=wstate, features=wide_stream(40),
        registry=reg, goodput=tracker, donate_state=False,
        measure_device_time=True,
    )
    healthy_ratio = tracker.report()["goodput_ratio"]
    report = watchdog.evaluate(now=100.0)
    assert not report["breached"], (
        f"healthy run (ratio {healthy_ratio:.3f}) must not breach: "
        f"{report['breached']}"
    )
    run_step_trainer(
        step_fn=wide_step, state=wstate,
        features=wide_stream(30, stall=stall_s),
        registry=reg, goodput=tracker, donate_state=False,
        measure_device_time=True,
    )
    # first post-collapse evaluation one fast window later: the healthy
    # sample has aged out, the collapsed ratio fills both windows
    report = watchdog.evaluate(now=110.0)
    assert "train_goodput" in report["breached"], (
        f"goodput collapse (ratio "
        f"{tracker.report()['goodput_ratio']:.3f}) did not breach: "
        f"{report}"
    )
    print(json.dumps({
        "metric": "train_goodput_slo_breached",
        "value": 1,
        "goodput_ratio": tracker.report()["goodput_ratio"],
        "unit": "bool",
    }))


def overlap_leg() -> None:
    """``UNIONML_TPU_BENCH_PRESET=train_overlap``: the overlapped-training
    stack (docs/performance.md "Overlapped training") measured against
    its own serial twin on the SAME workload.

    Two elastic-trainer runs over an identical paced, checkpointed,
    gradient-accumulated stream:

    - **off** — inline feed, synchronous checkpoint commits
      (``checkpoint_backend="sync"``), serial accumulation;
    - **on**  — ``double_buffer=True`` (threaded donated feed),
      ``overlap_grads=True`` (deferred-consumption scan), async
      background commits.

    Asserted, not just reported: bit-identical final state (overlap is
    scheduling, never numerics), the ``checkpoint`` + ``data_wait``
    buckets shrinking and ``host_to_device`` draining to zero,
    attribution ≥ 95% in BOTH modes, and overlap-on finishing faster —
    the paced feed gives the on-leg a structural, not statistical,
    wall-clock advantage.
    """
    import tempfile

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from flax import linen as nn

    from unionml_tpu.elastic import run_elastic_trainer
    from unionml_tpu.goodput import GoodputTracker
    from unionml_tpu.models.train import classification_step, create_train_state
    from unionml_tpu.telemetry import (
        FlightRecorder, MetricsRegistry, TraceRecorder,
    )

    class _Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(2048)(x)
            x = nn.relu(x)
            return nn.Dense(4)(x)

    net = _Net()
    rng = np.random.default_rng(0)
    n_steps, accum, micro = 40, 2, 32
    # per-batch host production cost (loader/augment), sized BELOW the
    # ~4 ms step so the threaded feed can fully hide it — overlap can
    # only drain host cost up to the compute duration
    pace_s = 0.003
    batches = [
        (
            rng.normal(size=(accum * micro, 256)).astype(np.float32),
            rng.integers(0, 4, size=(accum * micro,)).astype(np.int32),
        )
        for _ in range(n_steps)
    ]

    def stream(start_step):
        for i in range(start_step, n_steps):
            time.sleep(pace_s)  # the host-side cost the feed can overlap
            yield batches[i]

    # ONE step-function object for every run: _jitted caches per function
    # identity, so the warm-up runs below can only warm the measured legs
    # if they share this object (each mode still compiles its own
    # executable under its overlap/donate cache key)
    step_fn = classification_step(net, accumulate_steps=accum)

    def run(overlap: bool):
        reg = MetricsRegistry()
        tracker = GoodputTracker(
            registry=reg, tracer=TraceRecorder(registry=reg),
            flight=FlightRecorder(),
        )
        state = create_train_state(
            net, batches[0][0][:4], learning_rate=1e-2, seed=1
        )
        t0 = time.perf_counter()
        state, steps = run_elastic_trainer(
            step_fn=step_fn,
            state=state, stream=stream,
            checkpoint_dir=tempfile.mkdtemp(prefix="train-overlap-"),
            checkpoint_every=5, batch_size=micro, accumulate_steps=accum,
            checkpoint_backend="async" if overlap else "sync",
            overlap_grads=overlap, double_buffer=overlap,
            goodput=tracker,
        )
        wall = time.perf_counter() - t0
        assert steps == n_steps, f"expected {n_steps} steps, ran {steps}"
        return tracker.report(), state, wall

    # warm the jit cache out of the comparison (both modes: serial and
    # overlapped executables live under different cache keys)
    run(False)
    run(True)
    off, state_off, wall_off = run(False)
    on, state_on, wall_on = run(True)

    # 1. loss parity: overlap must be a scheduling change only
    for a, b in zip(
        jax.tree_util.tree_leaves(state_off.params),
        jax.tree_util.tree_leaves(state_on.params),
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            "overlap-on final state diverged from the serial run"
        )

    # 2. the three attacked buckets shrink
    off_bad, on_bad = off["badput_s"], on["badput_s"]
    assert on_bad["checkpoint"] < off_bad["checkpoint"], (
        f"async commit did not shrink the checkpoint bucket: "
        f"{on_bad['checkpoint']:.4f}s vs {off_bad['checkpoint']:.4f}s"
    )
    assert off_bad["data_wait"] >= n_steps * pace_s * 0.8, (
        f"paced stream should dominate the off-leg data_wait bucket: "
        f"{off_bad['data_wait']:.4f}s"
    )
    assert on_bad["data_wait"] < off_bad["data_wait"] * 0.5, (
        f"threaded feed did not drain data_wait: "
        f"{on_bad['data_wait']:.4f}s vs {off_bad['data_wait']:.4f}s"
    )
    assert on_bad["host_to_device"] == 0.0 < off_bad["host_to_device"], (
        "threaded feed must take the device-put dispatch off the "
        f"critical path: on={on_bad['host_to_device']:.4f}s "
        f"off={off_bad['host_to_device']:.4f}s"
    )

    # 3. attribution identity holds in both modes
    for name, rep in (("off", off), ("on", on)):
        assert rep["attributed_fraction"] >= 0.95, (
            f"{name}-leg attribution {rep['attributed_fraction']:.1%} "
            "below the 95% bar"
        )

    # 4. the overlap pays for itself on wall clock (structural: the
    # paced feed + commit I/O now run behind compute)
    assert wall_on < wall_off, (
        f"overlap-on slower than off: {wall_on:.3f}s vs {wall_off:.3f}s"
    )

    samples = n_steps * accum * micro
    print(json.dumps({
        "metric": "train_overlap_samples_per_sec",
        "off": round(samples / wall_off, 1),
        "value": round(samples / wall_on, 1),
        "unit": "samples/sec",
    }))
    print(json.dumps({
        "metric": "train_overlap_badput_deltas_s",
        "value": {
            cause: round(off_bad[cause] - on_bad[cause], 4)
            for cause in ("checkpoint", "data_wait", "host_to_device")
        },
        "off_badput_s": off_bad,
        "on_badput_s": on_bad,
        "attributed_fraction": {
            "off": off["attributed_fraction"],
            "on": on["attributed_fraction"],
        },
        "loss_parity": "bit-identical",
        "unit": "seconds saved per 40-step run",
    }))


if __name__ == "__main__":
    preset = os.environ.get("UNIONML_TPU_BENCH_PRESET")
    if preset in ("train_goodput", "train_overlap"):
        if len(sys.argv) > 1:
            # hardcoded workload, same rule as the serve_latency legs
            raise SystemExit(
                f"UNIONML_TPU_BENCH_PRESET={preset} takes no CLI "
                f"flags (got {sys.argv[1:]}); its workload is hardcoded"
            )
        goodput_leg() if preset == "train_goodput" else overlap_leg()
    else:
        main()
