"""Training-throughput benchmarks beyond the headline ViT (bench.py).

Reproduces the remaining BASELINE.md training rows on one chip:

- ``bert_ft``  — BERT-base classification fine-tune (batch 32, seq 128),
  samples/sec/chip; the config that exposed the donated-optax-adamw
  pathology (BASELINE.md) — uses the donation-safe ``adamw`` chain.
- ``llama_lc`` — long-context LM training (0.19B-param Llama geometry,
  batch 2, seq 4096, Pallas flash attention), tokens/sec/chip.

Prints one JSON line per config. Timing follows the BASELINE.md
methodology: warmup, >=100-step window on TPU, end with a host readback
data-dependent on the final donated state.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def _time_steps(step, state, batch, steps, warmup):
    from benchmarks._timing import drain

    for _ in range(warmup):
        state, metrics = step(state, batch)
    drain(state)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch)
    drain(state)
    return time.perf_counter() - t0


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.models import (
        BertClassifier,
        BertConfig,
        Llama,
        LlamaConfig,
        classification_step,
        create_train_state,
        lm_step,
    )

    tiny = os.environ.get("UNIONML_TPU_BENCH_PRESET") == "tiny" or (
        jax.default_backend() == "cpu"
    )
    steps, warmup = (3, 1) if tiny else (100, 10)
    rng = np.random.default_rng(0)

    # -- BERT-base fine-tune ------------------------------------------- #
    bcfg = BertConfig.tiny() if tiny else BertConfig.base(num_classes=2)
    batch, seq = (4, 16) if tiny else (32, 128)
    bert = BertClassifier(bcfg)
    ids = jnp.asarray(rng.integers(0, bcfg.vocab_size, size=(batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, 2, size=(batch,)), jnp.int32)
    state = create_train_state(bert, ids[:1], learning_rate=2e-5)
    step = jax.jit(classification_step(bert), donate_argnums=0)
    dt = _time_steps(step, state, (ids, labels), steps, warmup)
    print(json.dumps({
        "metric": "bert_ft_train_samples_per_sec_per_chip",
        "batch": batch, "seq": seq,
        "value": round(batch * steps / dt, 1),
        "unit": "samples/sec/chip",
    }))

    # -- long-context Llama LM ----------------------------------------- #
    if tiny:
        lcfg = LlamaConfig.tiny(vocab_size=256)
        batch, seq = 2, 64
    else:
        # ~0.19B params: 12 x 768 Llama geometry, flash attention
        lcfg = LlamaConfig(
            vocab_size=32_000, hidden_dim=768, num_layers=12, num_heads=12,
            num_kv_heads=4, mlp_dim=2048, max_len=4096, attn_impl="flash",
        )
        batch, seq = 2, 4096
    lm = Llama(lcfg)
    tokens = jnp.asarray(rng.integers(0, lcfg.vocab_size, size=(batch, seq)), jnp.int32)
    state = create_train_state(lm, tokens[:1, :8], learning_rate=1e-3)
    step = jax.jit(lm_step(lm), donate_argnums=0)
    dt = _time_steps(step, state, tokens, steps, warmup)
    print(json.dumps({
        "metric": "llama_lc_train_tokens_per_sec_per_chip",
        "batch": batch, "seq": seq,
        "value": round(batch * (seq - 1) * steps / dt, 1),
        "unit": "tokens/sec/chip",
    }))

    # -- QLoRA fine-tune (UNIONML_TPU_BENCH_PRESET=qlora_8b) ------------ #
    # The serving flagship run in reverse: fine-tune Llama-3-8B on ONE
    # chip. Full fine-tuning cannot fit (bf16 params + fp32 master + adam
    # m/v ~ 96 GB); QLoRA does: the int8 base (~8.6 GB, the same tree the
    # serving path streams) is frozen, and only rank-16 adapters (~42M
    # params, ~0.5 GB with adam state) train. Per-block remat keeps
    # activations at one block.
    if os.environ.get("UNIONML_TPU_BENCH_PRESET") == "qlora_8b" or tiny:
        from benchmarks.serve_latency import random_quantized_params

        from unionml_tpu.models import create_lora_train_state

        if tiny:
            qcfg = LlamaConfig.tiny(vocab_size=256, quantized=True)
            batch, seq, rank = 2, 32, 4
        else:
            qcfg = LlamaConfig(
                quantized=True, remat=True, attn_impl="flash", max_len=2048
            )
            batch, seq, rank = 1, 1024, 16
        base = random_quantized_params(Llama(qcfg))
        import dataclasses

        lcfg = dataclasses.replace(qcfg, lora_rank=rank)
        lora_llama = Llama(lcfg)
        state = create_lora_train_state(
            lora_llama, jnp.zeros((1, 8), jnp.int32), base_params=base,
            learning_rate=1e-4,
        )
        del base  # the state holds the only reference now
        tokens = jnp.asarray(
            rng.integers(0, qcfg.vocab_size, size=(batch, seq)), jnp.int32
        )
        step = jax.jit(lm_step(lora_llama), donate_argnums=0)
        n_steps = steps if tiny else 30  # ~0.5 s/step at 8B: 30 suffice
        dt = _time_steps(step, state, tokens, n_steps, warmup if tiny else 5)
        print(json.dumps({
            "metric": "qlora_8b_train_tokens_per_sec_per_chip",
            "batch": batch, "seq": seq, "lora_rank": rank,
            "value": round(batch * (seq - 1) * n_steps / dt, 1),
            "unit": "tokens/sec/chip",
        }))

    # -- long-context scaling (UNIONML_TPU_BENCH_LC_SCALE=1) ------------ #
    # tokens/sec vs sequence length at a constant 8192-token batch:
    # flash attention keeps memory linear in seq; per-block remat trades
    # recompute for activation memory at 16k+
    if os.environ.get("UNIONML_TPU_BENCH_LC_SCALE") and not tiny:
        for b, s, remat, accum in (
            (1, 8192, False, 1),
            (1, 16384, True, 1),
            # HBM caps the 16k config at microbatch 1; gradient
            # accumulation restores an effective batch of 4 with the
            # same activation footprint — the accumulate_steps knob's
            # long-context cost is this row vs the one above
            (1, 16384, True, 4),
        ):
            scfg = LlamaConfig(**{**lcfg.__dict__, "max_len": s, "remat": remat})
            lm_s = Llama(scfg)
            toks = jnp.asarray(
                rng.integers(0, scfg.vocab_size, size=(b * accum, s)), jnp.int32
            )
            if accum > 1:
                toks = toks.reshape(accum, b, s)
            st = create_train_state(
                lm_s, jnp.zeros((1, 8), jnp.int32), learning_rate=1e-3
            )
            stp = jax.jit(lm_step(lm_s, accumulate_steps=accum), donate_argnums=0)
            n_steps = max(20, steps // 4)  # longer steps: fewer suffice
            dt = _time_steps(stp, st, toks, n_steps, max(2, warmup // 2))
            print(json.dumps({
                "metric": "llama_lc_scale_tokens_per_sec_per_chip",
                "batch": b, "seq": s, "remat": remat,
                "accumulate_steps": accum,
                "value": round(b * accum * (s - 1) * n_steps / dt, 1),
                "unit": "tokens/sec/chip",
            }))


if __name__ == "__main__":
    main()
