"""End-to-end HTTP serving p50 (the BASELINE.json north-star metric at
its true boundary: "FastAPI predictor p50 latency").

serve_latency.py times ``generate()`` directly; THIS script measures the
full request path — HTTP transport -> ServingApp -> row-list
micro-batcher -> bucketed jitted prefill+decode -> response — for a
single client (pure latency) and for concurrent clients (the
micro-batcher coalescing window). One JSON line per scenario.

Usage (on the TPU)::

    python benchmarks/serve_http.py [--requests 20] [--clients 8]
    UNIONML_TPU_BENCH_PRESET=tiny JAX_PLATFORMS=cpu python benchmarks/serve_http.py
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=20)
    parser.add_argument("--clients", type=int, default=8)
    parser.add_argument("--prompt-len", type=int, default=64)
    parser.add_argument("--new-tokens", type=int, default=32)
    args = parser.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu import Dataset, Model
    from unionml_tpu.models import (
        LLAMA_QUANT_PATTERNS,
        Llama,
        LlamaConfig,
        make_lm_predictor,
        quantize_params,
    )
    from unionml_tpu.serving.http import ServingApp
    from benchmarks.serve_latency import serving_config

    preset = os.environ.get(
        "UNIONML_TPU_BENCH_PRESET",
        "tiny" if jax.default_backend() == "cpu" else "serve_1p5b",
    )
    if preset == "tiny":
        args.requests = min(args.requests, 3)
    cfg = serving_config(preset)
    qcfg = LlamaConfig(**{**cfg.__dict__, "quantized": True})
    qmodule = Llama(qcfg)

    if preset == "serve_8b":
        # synthetic int8 weights: an 8B master tree can't be materialized
        # on-chip to quantize from (see serve_latency.random_quantized_params)
        from benchmarks.serve_latency import random_quantized_params

        qparams = random_quantized_params(qmodule)
    else:
        # int8 artifact, exactly the serve_latency production path
        fp_params = jax.jit(Llama(cfg).init)(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        qparams = quantize_params(fp_params, LLAMA_QUANT_PATTERNS)

    dataset = Dataset(name="http_bench_data", targets=[])

    @dataset.reader
    def reader() -> list:
        return []

    model = Model(name="http_bench_lm", init=lambda: qparams, dataset=dataset)

    predict = make_lm_predictor(
        qmodule, max_new_tokens=args.new_tokens,
        bucket_lens=(args.prompt_len,),
    )

    @model.trainer
    def trainer(params: dict, features: list) -> dict:
        return params

    @model.predictor
    def predictor(params: dict, prompts: list) -> list:
        return predict(params, prompts)

    from unionml_tpu.model import ModelArtifact

    model.artifact = ModelArtifact(qparams, {}, {})

    serving = ServingApp(
        model, batch=True, row_lists=True, max_wait_ms=3.0,
        # pre-compile every (bucket, batch-power) executable: without
        # this, first-hit shapes stall live requests behind ~20 s XLA
        # compiles (measured 17.9 s p95 under 8 concurrent clients)
        warmup=lambda params: predict.warmup(params, max_batch=args.clients),
    )
    host, port = serving.serve(port=0, blocking=False)

    rng = np.random.default_rng(0)
    prompt = rng.integers(1, cfg.vocab_size, size=(args.prompt_len,)).tolist()
    body = json.dumps({"features": [prompt]}).encode()

    def request() -> float:
        req = urllib.request.Request(
            f"http://{host}:{port}/predict", data=body,
            headers={"Content-Type": "application/json"},
        )
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=300) as resp:
            out = json.loads(resp.read())
        assert isinstance(out, list) and len(out[0]) == args.new_tokens
        return (time.perf_counter() - t0) * 1e3

    request()  # warmup/compile

    # single client: pure request latency
    lat = sorted(request() for _ in range(args.requests))
    p50 = lat[len(lat) // 2]
    p95 = lat[max(0, math.ceil(0.95 * len(lat)) - 1)]
    print(json.dumps({
        "metric": f"{preset}_http_p50_ms", "clients": 1,
        "value": round(p50, 1), "p95_ms": round(p95, 1), "unit": "ms",
    }))

    # concurrent clients: the micro-batcher coalesces in-flight requests
    all_lat: list = []
    lock = threading.Lock()

    def client():
        mine = [request() for _ in range(args.requests)]
        with lock:
            all_lat.extend(mine)

    threads = [threading.Thread(target=client) for _ in range(args.clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    all_lat.sort()
    p50 = all_lat[len(all_lat) // 2]
    p95 = all_lat[max(0, math.ceil(0.95 * len(all_lat)) - 1)]
    n = args.clients * args.requests
    print(json.dumps({
        "metric": f"{preset}_http_p50_ms", "clients": args.clients,
        "value": round(p50, 1), "p95_ms": round(p95, 1),
        "requests_per_sec": round(n / wall, 2),
        "tokens_per_sec": round(n * args.new_tokens / wall, 1),
        "unit": "ms",
    }))
    serving.shutdown()


if __name__ == "__main__":
    main()
