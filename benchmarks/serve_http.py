"""End-to-end HTTP serving p50 (the BASELINE.json north-star metric at
its true boundary: "FastAPI predictor p50 latency").

serve_latency.py times ``generate()`` directly; THIS script measures the
full request path — HTTP transport -> ServingApp -> batching layer ->
device -> response — for a single client (pure latency) and for
concurrent clients. Two batching modes:

- ``--mode batcher``: the row-list micro-batcher (full-batch generate;
  a late request waits out the whole in-flight decode),
- ``--mode engine`` (default): the continuous-batching DecodeEngine
  (requests join at chunk boundaries — the p95 fix).

Each scenario prints one JSON line; the concurrent line includes the
``/stats`` split (queue-wait vs prefill vs decode) so tail latency is
attributable.

Usage (on the TPU)::

    python benchmarks/serve_http.py [--requests 20] [--clients 8] [--mode engine|batcher]
    UNIONML_TPU_BENCH_PRESET=tiny JAX_PLATFORMS=cpu python benchmarks/serve_http.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--requests", type=int, default=20)
    parser.add_argument("--clients", type=int, default=8)
    parser.add_argument("--prompt-len", type=int, default=64)
    parser.add_argument("--new-tokens", type=int, default=32)
    parser.add_argument(
        "--mode", choices=("engine", "batcher", "auto"), default="auto",
        help="auto (default) measures host<->device RTT and one decode "
        "chunk at startup and picks the measured winner "
        "(unionml_tpu.serving.auto); the decision and its evidence land "
        "in /stats",
    )
    parser.add_argument(
        "--spec-k", type=int, default=4,
        help="speculate_k for the serve_spec preset",
    )
    parser.add_argument("--chunk-steps", type=int, default=8)
    parser.add_argument(
        "--pipeline-depth", type=int, default=None,
        help="decode chunks in flight; default scales to cover ~120 ms of "
        "round-trip with this model's chunk compute (big models need "
        "shallow pipelines or joins queue behind the chunk backlog)",
    )
    parser.add_argument(
        "--prefill-chunk", type=int, default=None,
        help="engine mode: admit buckets larger than this in chunked "
        "prefill programs so resident decodes never stall behind a long "
        "prompt (default: 512 when --prompt-len >= 4096, like the "
        "generator's long-context rule; 0 disables)",
    )
    parser.add_argument(
        "--prefill-impl", choices=("cached", "flash"), default="cached",
        help="flash = Pallas monolithic prefill for FULL prefills "
        "(BASELINE.md round 5). Unlike serve_latency, this COMPOSES with "
        "--prefill-chunk here: bucketed serving runs flash on monolithic "
        "admissions while chunk-ruled long buckets stay chunked-cached. "
        "Ignored by the speculative presets (their module pair is built "
        "separately).",
    )
    parser.add_argument(
        "--checkpoint", default=None,
        help="HF safetensors checkpoint directory — serve REAL weights, "
        "streamed to int8 on load (models/convert.py); geometry comes "
        "from its config.json and overrides the preset's",
    )
    parser.add_argument(
        "--open-rate", type=float, default=0.0,
        help="also run an open-loop scenario: Poisson arrivals at this "
        "rate (req/s) — the workload where step-boundary joins beat the "
        "full-batch barrier. 0 skips it.",
    )
    args = parser.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu import Dataset, Model
    from unionml_tpu.models import (
        LLAMA_QUANT_PATTERNS,
        Llama,
        LlamaConfig,
        make_lm_predictor,
        quantize_params,
    )
    from unionml_tpu.serving.http import ServingApp
    from benchmarks.serve_latency import serving_config

    preset = os.environ.get(
        "UNIONML_TPU_BENCH_PRESET",
        "tiny" if jax.default_backend() == "cpu" else "serve_1p5b",
    )
    if preset == "tiny":
        args.requests = min(args.requests, 3)
    spec_predict = None
    spec_modules = None
    if preset in ("serve_spec", "tiny_spec"):
        if args.checkpoint:
            # silently serving random weights while reporting them as
            # the checkpoint's numbers would poison the record
            raise SystemExit(
                "--checkpoint is not supported with the speculative "
                "presets (they build a synthetic target/draft pair)"
            )
        # speculative decoding at the HTTP boundary: target + draft pair
        # behind make_speculative_predictor (batcher mode) or the
        # speculative DecodeEngine (engine mode — per-slot draft rounds
        # with one shared verify, round-5)
        from unionml_tpu.models import make_speculative_predictor

        if preset == "tiny_spec":
            t_cfg = LlamaConfig.tiny(vocab_size=512)
            d_cfg = LlamaConfig.tiny(
                vocab_size=512, hidden_dim=32, num_layers=1, num_heads=2,
                num_kv_heads=1, mlp_dim=64,
            )
            t_module, d_module = Llama(t_cfg), Llama(d_cfg)
            toks = jnp.zeros((1, 8), jnp.int32)
            qparams = {
                "target": t_module.init(jax.random.PRNGKey(0), toks)["params"],
                "draft": d_module.init(jax.random.PRNGKey(1), toks)["params"],
            }
            args.requests = min(args.requests, 3)
        else:
            from benchmarks.serve_latency import random_quantized_params

            t_cfg = LlamaConfig(
                **{**serving_config("serve_8b").__dict__, "quantized": True}
            )
            d_cfg = LlamaConfig(
                **{**serving_config("serve_1p5b").__dict__, "quantized": True}
            )
            t_module, d_module = Llama(t_cfg), Llama(d_cfg)
            qparams = {
                "target": random_quantized_params(t_module),
                "draft": random_quantized_params(d_module),
            }
        qcfg = t_cfg
        if args.mode == "engine":
            # the speculative ENGINE: constructed below in the unified
            # engine block, where --pipeline-depth/--prefill-chunk/
            # --chunk-steps are resolved (round-5)
            spec_modules = (t_module, d_module)
        else:
            spec_predict = make_speculative_predictor(
                t_module, d_module, max_new_tokens=args.new_tokens,
                bucket_lens=(args.prompt_len,), speculate_k=args.spec_k,
            )
            if args.mode != "batcher":
                print(json.dumps({
                    "metric": "serving_mode_auto", "mode": "batcher",
                    "rule": "speculative predictor defaults to the "
                            "micro-batcher; pass --mode engine for the "
                            "speculative engine",
                }))
                args.mode = "batcher"

    if spec_predict is not None or spec_modules is not None:
        cfg = None      # the spec path holds its own module pair;
        qmodule = None  # the per-preset serving config never applies
    elif (cfg := serving_config(preset)) and args.checkpoint:
        if getattr(cfg, "weight_bits", 8) == 4:
            raise SystemExit(
                "--checkpoint streams to int8; the serve_8b_w4 preset "
                "would mislabel an int8 run — use serve_8b with "
                "--checkpoint, or the w4 preset without it"
            )
        # REAL weights: geometry from the checkpoint's config.json,
        # serving knobs (cache size, kv_quant, attention impl) from the
        # preset; kernels stream to int8 on load without an fp tree ever
        # materializing (models/convert.py)
        from unionml_tpu.models import load_llama_checkpoint

        qparams, qcfg = load_llama_checkpoint(
            args.checkpoint, quantize=True, quantized=True,
            max_len=cfg.max_len, kv_quant=cfg.kv_quant,
            attn_impl=cfg.attn_impl,
        )
        if args.prefill_impl != "cached":
            import dataclasses

            qcfg = dataclasses.replace(qcfg, prefill_impl=args.prefill_impl)
        qmodule = Llama(qcfg)
    else:
        qcfg = LlamaConfig(**{
            **cfg.__dict__, "quantized": True,
            "prefill_impl": args.prefill_impl,
        })
        qmodule = Llama(qcfg)
        if preset.startswith("serve_8b"):
            # synthetic quantized weights: an 8B master tree can't be
            # materialized on-chip to quantize from (see
            # serve_latency.random_quantized_params); serve_8b_w4 runs
            # the packed-int4 decode kernel
            from benchmarks.serve_latency import random_quantized_params

            qparams = random_quantized_params(qmodule)
        else:
            # int8 artifact, exactly the serve_latency production path
            fp_params = jax.jit(Llama(cfg).init)(
                jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
            )["params"]
            qparams = quantize_params(fp_params, LLAMA_QUANT_PATTERNS)

    dataset = Dataset(name="http_bench_data", targets=[])

    @dataset.reader
    def reader() -> list:
        return []

    model = Model(name="http_bench_lm", init=lambda: qparams, dataset=dataset)

    @model.trainer
    def trainer(params: dict, features: list) -> dict:
        return params

    mode_decision = None
    if args.mode == "auto":
        # encode the measured crossover (BASELINE.md round 3) instead of
        # making the operator choose blind: engine iff one decode chunk
        # costs at least one host<->device round trip
        from unionml_tpu.serving.auto import choose_serving_mode

        mode_decision = choose_serving_mode(
            qmodule, qparams, chunk_steps=args.chunk_steps
        )
        args.mode = mode_decision["mode"]
        print(json.dumps({"metric": "serving_mode_auto", **mode_decision}))

    if args.mode == "engine":
        from unionml_tpu.serving.engine import DecodeEngine

        depth = args.pipeline_depth
        if depth is None:
            # cover one ~120 ms RTT of backlog, no more: deeper pipelines
            # make joining prefills queue behind the whole chunk backlog.
            # Keyed on actual geometry, not the preset name: --checkpoint
            # can swap in an 8B-class model under any preset
            per_step_ms = 11.0 if qcfg.hidden_dim >= 4096 else 3.3
            depth = max(2, int(round(120.0 / (args.chunk_steps * per_step_ms))))
        prefill_chunk = args.prefill_chunk
        if prefill_chunk is None:
            # auto only when the bucket divides evenly — an explicit flag
            # still surfaces DecodeEngine's divisibility error
            prefill_chunk = (
                512 if args.prompt_len >= 4096 and args.prompt_len % 512 == 0
                else 0
            )
        common = dict(
            slots=args.clients, max_new_tokens=args.new_tokens,
            prompt_buckets=(args.prompt_len,), pipeline_depth=depth,
            prefill_chunk=prefill_chunk or None,
        )
        if spec_modules is not None:
            # the speculative engine: same flag wiring as the plain
            # engine (chunked admission composes with speculation);
            # chunk_steps counts ROUNDS here, so scale the decode-steps
            # flag down by the tokens a round can emit
            t_mod, d_mod = spec_modules
            engine = DecodeEngine(
                t_mod, draft_module=d_mod, speculate_k=args.spec_k,
                chunk_steps=max(1, round(args.chunk_steps / (args.spec_k + 1))),
                **common,
            )
        else:
            engine = DecodeEngine(
                qmodule, chunk_steps=args.chunk_steps, **common,
            )

        @model.predictor
        def predictor(params: dict, prompts: list) -> list:
            return engine.generate(params, prompts)

        serving_kwargs = dict(
            warmup=lambda params: engine.warmup(params), stats=engine.stats,
            # SSE token streaming (POST /predict/stream): TTFT ~ queue +
            # prefill instead of the whole generation
            stream=lambda params, prompts: engine.generate_stream(
                params, prompts[0]
            ),
        )
    else:
        if spec_predict is not None:
            predict = spec_predict
        else:
            predict = make_lm_predictor(
                qmodule, max_new_tokens=args.new_tokens,
                bucket_lens=(args.prompt_len,),
            )

        @model.predictor
        def predictor(params: dict, prompts: list) -> list:
            return predict(params, prompts)

        serving_kwargs = dict(
            batch=True, row_lists=True, max_wait_ms=3.0,
            # never coalesce beyond the warmed shapes: an open-loop burst
            # can queue more than `clients` rows, and an unwarmed bucket
            # stalls the batch behind a ~20-40 s XLA compile
            max_batch_size=args.clients,
            # pre-compile every (bucket, batch-power) executable: without
            # this, first-hit shapes stall live requests behind ~20 s XLA
            # compiles (measured 17.9 s p95 under 8 concurrent clients)
            warmup=lambda params: predict.warmup(params, max_batch=args.clients),
        )

    from unionml_tpu.model import ModelArtifact

    model.artifact = ModelArtifact(qparams, {}, {})

    if mode_decision is not None:
        # /stats records the auto decision and its evidence
        serving_kwargs["extra_stats"] = {"mode_decision": mode_decision}
    serving = ServingApp(model, **serving_kwargs)
    host, port = serving.serve(port=0, blocking=False)

    rng = np.random.default_rng(0)
    prompt = rng.integers(1, qcfg.vocab_size, size=(args.prompt_len,)).tolist()
    body = json.dumps({"features": [prompt]}).encode()

    def request() -> float:
        req = urllib.request.Request(
            f"http://{host}:{port}/predict", data=body,
            headers={"Content-Type": "application/json"},
        )
        t0 = time.perf_counter()
        with urllib.request.urlopen(req, timeout=300) as resp:
            out = json.loads(resp.read())
        assert isinstance(out, list) and len(out[0]) == args.new_tokens
        return (time.perf_counter() - t0) * 1e3

    request()  # warmup/compile

    from unionml_tpu.serving._stats import percentile_summary

    def reset_stats():
        # each scenario's /stats must describe only that scenario, not
        # dilute its queue-wait/occupancy with warmup or earlier phases
        if args.mode == "engine":
            engine.reset_stats()
        else:
            serving.reset_stats()

    def fetch_stats() -> dict:
        with urllib.request.urlopen(
            f"http://{host}:{port}/stats", timeout=30
        ) as resp:
            stats = json.loads(resp.read())
        return {
            k: stats[k]
            for k in ("queue_wait_ms", "prefill_ms", "decode_ms",
                      "ttft_ms", "device_ms", "slot_occupancy",
                      "mode_decision")
            if k in stats
        }

    # single client: pure request latency
    lat = [request() for _ in range(args.requests)]
    s = percentile_summary(lat)
    print(json.dumps({
        "metric": f"{preset}_http_p50_ms", "mode": args.mode, "clients": 1,
        "prefill_impl": args.prefill_impl, "prefill_chunk": args.prefill_chunk,
        "value": s["p50"], "p95_ms": s["p95"], "unit": "ms",
    }))
    reset_stats()

    if args.mode == "engine":
        # streaming: time-to-first-token at the HTTP boundary (the UX
        # metric SSE exists for) vs the same request's full duration
        import http.client

        def stream_request():
            conn = http.client.HTTPConnection(host, port, timeout=300)
            t0 = time.perf_counter()
            conn.request(
                "POST", "/predict/stream", body=json.dumps({"features": prompt}),
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            assert resp.status == 200, resp.read()
            ttft = None
            n_tokens = 0
            buf = b""
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    break
                buf += chunk
                while b"\n\n" in buf:
                    event, buf = buf.split(b"\n\n", 1)
                    if not event.startswith(b"data: "):
                        continue
                    data = json.loads(event[len(b"data: "):])
                    if "tokens" in data:
                        if ttft is None:
                            ttft = (time.perf_counter() - t0) * 1e3
                        n_tokens += len(data["tokens"])
                    elif data.get("done"):
                        assert data["n_tokens"] == n_tokens == args.new_tokens
            conn.close()
            return ttft, (time.perf_counter() - t0) * 1e3

        stream_request()  # warm the path
        reset_stats()
        pairs = [stream_request() for _ in range(args.requests)]
        ttft_s = percentile_summary([p[0] for p in pairs])
        full_s = percentile_summary([p[1] for p in pairs])
        print(json.dumps({
            "metric": f"{preset}_http_ttft_ms", "mode": "engine-stream",
            "clients": 1, "value": ttft_s["p50"], "p95_ms": ttft_s["p95"],
            "full_response_p50_ms": full_s["p50"], "unit": "ms",
            "stats": fetch_stats(),
        }))
        reset_stats()

    # concurrent clients: the micro-batcher coalesces in-flight requests
    all_lat: list = []
    lock = threading.Lock()

    def client():
        mine = [request() for _ in range(args.requests)]
        with lock:
            all_lat.extend(mine)

    threads = [threading.Thread(target=client) for _ in range(args.clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    s = percentile_summary(all_lat)
    n = args.clients * args.requests
    print(json.dumps({
        "metric": f"{preset}_http_p50_ms", "mode": args.mode,
        "clients": args.clients,
        "prefill_impl": args.prefill_impl, "prefill_chunk": args.prefill_chunk,
        "value": s["p50"], "p95_ms": s["p95"],
        "requests_per_sec": round(n / wall, 2),
        "tokens_per_sec": round(n * args.new_tokens / wall, 1),
        "unit": "ms",
        "stats": fetch_stats(),
    }))
    if args.open_rate > 0:
        # open loop: arrivals are scheduled, not gated on completions —
        # a late arrival during an in-flight decode exposes the batcher's
        # full-batch barrier (it waits the whole generation out) vs the
        # engine's chunk-boundary join
        reset_stats()
        n_open = args.clients * args.requests
        gaps = np.random.default_rng(1).exponential(1.0 / args.open_rate, n_open)
        arrivals = np.cumsum(gaps)
        open_lat: list = []

        def timed_request(delay: float):
            time.sleep(max(0.0, delay))
            open_lat.append(request())

        start = time.perf_counter()
        threads = [
            threading.Thread(target=timed_request, args=(a - (time.perf_counter() - start),))
            for a in arrivals
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - start
        s = percentile_summary(open_lat)
        print(json.dumps({
            "metric": f"{preset}_http_open_p50_ms", "mode": args.mode,
            "offered_rps": args.open_rate,
            "value": s["p50"], "p95_ms": s["p95"],
            "requests_per_sec": round(n_open / wall, 2), "unit": "ms",
            "stats": fetch_stats(),
        }))
    serving.shutdown()
    if args.mode == "engine":
        engine.close()


if __name__ == "__main__":
    main()
