"""Speculative decoding INSIDE the continuous-batching engine, measured.

Round-4 left speculation usable only through the full-batch
micro-batcher; the engine — the mode that wins exactly where
speculation matters (8B-class, staggered traffic) — could not
speculate. Round 5 adds per-slot draft chunks + one shared multi-token
verify per round to :class:`~unionml_tpu.serving.engine.DecodeEngine`;
this bench measures it on the real chip.

Acceptance is CONTROLLED with the ``benchmarks/speculative.py``
BoostedTarget instrument (synthetic weights agree at chance, so organic
acceptance is ~0): the target's logits are nudged toward the next input
token by ``boost``, which in the verify shape is exactly the draft's
proposal — sweeping ``boost`` sweeps acceptance, REPORTED from the
engine's own ``/stats`` acceptance counter, while every wall-clock
number is the genuine program.

Scenarios (one JSON line each; closed-loop, staggered clients):

- plain engine (no draft): the baseline p50/p95;
- speculative engine, 0.3B int8 draft, k=4: boost sweep → (observed
  acceptance, p50/p95, ms/round) — where the crossover lands.

Usage::

    python benchmarks/speculative_engine.py            # on the TPU
    UNIONML_TPU_BENCH_PRESET=tiny JAX_PLATFORMS=cpu \
        python benchmarks/speculative_engine.py
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from benchmarks.serve_latency import serving_config
    from benchmarks.speculative import make_boosted_target
    from unionml_tpu.models import Llama, LlamaConfig
    from unionml_tpu.serving.engine import DecodeEngine

    tiny = os.environ.get("UNIONML_TPU_BENCH_PRESET") == "tiny" or (
        jax.default_backend() == "cpu"
    )
    t_preset = "tiny"
    if tiny:
        t_cfg = LlamaConfig.tiny(vocab_size=512)
        d_cfg = LlamaConfig.tiny(
            vocab_size=512, hidden_dim=32, num_layers=1, num_heads=2,
            num_kv_heads=1, mlp_dim=64,
        )
        toks = jnp.zeros((1, 8), jnp.int32)
        t_params = Llama(t_cfg).init(jax.random.PRNGKey(0), toks)["params"]
        d_params = Llama(d_cfg).init(jax.random.PRNGKey(1), toks)["params"]
        slots, prompt_len, new_tokens, reqs, boosts = 2, 8, 8, 2, (0.0, 1e9)
    else:
        from benchmarks.serve_latency import random_quantized_params

        # UNIONML_TPU_SPEC_TARGET=serve_8b_w4 runs the packed-int4
        # target (the round-4 north-star artifact) under speculation.
        # Validated: serving_config falls back to 1.5B for unknown
        # names, which would silently poison the record with a
        # mislabeled target
        t_preset = os.environ.get("UNIONML_TPU_SPEC_TARGET", "serve_8b")
        if t_preset not in ("serve_8b", "serve_8b_w4", "serve_1p5b"):
            raise SystemExit(
                f"unknown UNIONML_TPU_SPEC_TARGET {t_preset!r} (use "
                "serve_8b, serve_8b_w4, or serve_1p5b)"
            )
        # env knobs (read together — they size each other):
        # PROMPT_LEN >= 1024 turns on the measured long-context levers;
        # NEW_TOKENS: long OUTPUTS are where decode (the part
        # speculation accelerates) dominates the request;
        # SLOTS: fewer slots shrink the resident caches (the HBM lever
        # for 8B x long context on one chip);
        # PREFILL_CHUNK: chunked admission (the 8B-at-4k path — the
        # combined target+draft flash-monolithic admission program
        # exceeds the compiler at 8B)
        prompt_len = int(os.environ.get("UNIONML_TPU_SPEC_PROMPT_LEN", "64"))
        new_tokens = int(os.environ.get("UNIONML_TPU_SPEC_NEW_TOKENS", "32"))
        slots = int(os.environ.get("UNIONML_TPU_SPEC_SLOTS", "8"))
        prefill_chunk = (
            int(os.environ.get("UNIONML_TPU_SPEC_PREFILL_CHUNK", "0")) or None
        )
        # the engine only chunks buckets LARGER than the chunk — mirror
        # its admission rule, or a too-big chunk value would both admit
        # monolithically AND disable flash (measuring the worst of both)
        chunked = prefill_chunk is not None and prompt_len > prefill_chunk
        long_ctx = prompt_len >= 1024
        base_cfg = serving_config(t_preset)
        # cache must cover bucket + generation + the engine's in-flight
        # slack rows ((pipeline_depth + 1) * chunk_steps * round stride)
        need_len = prompt_len + new_tokens + 128
        lc = (
            {
                "kv_quant": True,
                # flash only fires on MONOLITHIC admissions — under
                # chunked admission leave it off so the JSON rows don't
                # claim an impl that never engaged
                **({} if chunked else {"prefill_impl": "flash"}),
                "max_len": max(base_cfg.max_len, need_len),
            }
            if long_ctx
            else {}
        )
        t_cfg = LlamaConfig(**{**base_cfg.__dict__, "quantized": True, **lc})
        # ~0.3B draft (the round-4 curve's identified lever); its cache
        # must cover the same context as the target's
        d_cfg = LlamaConfig(**{
            **dict(
                vocab_size=128_256, hidden_dim=1024, num_layers=10,
                num_heads=16, num_kv_heads=8, mlp_dim=2816,
                quantized=True,
            ),
            **lc,
            "max_len": max(2048, need_len),
        })
        t_params = random_quantized_params(Llama(t_cfg))
        d_params = random_quantized_params(Llama(d_cfg))
        reqs = 2
        # boost sweep: 0 (chance), mid points, and "accept everything";
        # override with UNIONML_TPU_SPEC_BOOSTS=2.0,3.5 to refine
        env = os.environ.get("UNIONML_TPU_SPEC_BOOSTS")
        boosts = (
            tuple(float(b) for b in env.split(","))
            if env else (0.0, 5.0, 8.0, 12.0, 1e9)
        )

    if tiny:
        prefill_chunk = None
    k = 4
    chunk_rounds = 2          # speculative rounds per dispatched chunk
    rng = np.random.default_rng(0)
    prompts = rng.integers(
        1, min(t_cfg.vocab_size, d_cfg.vocab_size), size=(slots, prompt_len)
    )

    def closed_loop(gen_fn) -> dict:
        lat = []
        lock = threading.Lock()

        def client(i):
            time.sleep(0.03 * i)   # staggered: the engine's regime
            for _ in range(reqs):
                t0 = time.perf_counter()
                gen_fn([prompts[i].tolist()])
                with lock:
                    lat.append(time.perf_counter() - t0)

        threads = [threading.Thread(target=client, args=(i,)) for i in range(slots)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        from unionml_tpu.serving._stats import percentile_summary

        # shared nearest-rank formula (int(0.95*n) indexed the MAXIMUM
        # for small windows — the bias _stats.percentile_summary fixes)
        s = percentile_summary([v * 1e3 for v in lat])
        return {"p50_ms": s["p50"], "p95_ms": s["p95"], "n": s["n"]}

    target = Llama(t_cfg)
    draft = Llama(d_cfg)

    # ---- baseline: plain engine, no draft ----
    plain = DecodeEngine(
        target, slots=slots, max_new_tokens=new_tokens,
        prompt_buckets=(prompt_len,), chunk_steps=8, pipeline_depth=2,
        prefill_chunk=prefill_chunk,
    )
    plain.warmup(t_params)
    closed_loop(lambda p: plain.generate(t_params, p))
    base = closed_loop(lambda p: plain.generate(t_params, p))
    plain.close()
    print(json.dumps({
        "metric": "spec_engine_plain_baseline", "target": t_preset,
        "prompt_len": prompt_len, "kv_quant": bool(t_cfg.kv_quant),
        "prefill_impl": t_cfg.prefill_impl, **base,
    }), flush=True)

    # ---- speculative engine over the boosted target ----
    boosted = make_boosted_target(t_cfg)
    engine = DecodeEngine(
        boosted, draft_module=draft, speculate_k=k, slots=slots,
        max_new_tokens=new_tokens, prompt_buckets=(prompt_len,),
        chunk_steps=chunk_rounds, pipeline_depth=2,
        prefill_chunk=prefill_chunk,
    )
    for boost in boosts:
        params = {
            "target": {"inner": t_params, "boost": jnp.float32(boost)},
            "draft": d_params,
        }
        engine.warmup(params)          # first boost compiles; rest reuse
        closed_loop(lambda p: engine.generate(params, p))
        engine.reset_stats()
        t0 = time.perf_counter()
        res = closed_loop(lambda p: engine.generate(params, p))
        wall = time.perf_counter() - t0
        stats = engine.stats()
        spec = stats["speculative"]
        ms_per_round = round(wall * 1e3 / max(1, spec["rounds"] / slots), 2)
        print(json.dumps({
            "metric": "spec_engine_boosted",
            "target": t_preset,
            "prompt_len": prompt_len,
            "k": k,
            "boost": boost,
            "acceptance": spec["acceptance_rate"],
            **res,
            "rounds": spec["rounds"],
            "ms_per_slot_round": ms_per_round,
            "speedup_vs_plain_p50": round(base["p50_ms"] / res["p50_ms"], 2),
        }), flush=True)
        # drain between sweep points so bind() can swap cleanly
    engine.close()


if __name__ == "__main__":
    main()
