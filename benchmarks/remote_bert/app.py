"""BERT-base fine-tune through the remote lifecycle (BASELINE.json #4).

The app is deployed and executed via ``Model.remote_deploy`` →
``Model.remote_train`` (reference lifecycle: model.py:672-796): the
RUNNER process — not this driver — runs the timed fine-tune loop on the
TPU, and the measured samples/sec/chip travels back as the execution's
metrics, so the recorded number is sourced from the remote execution
itself. Run on the TPU host::

    python benchmarks/remote_bert/app.py

CPU smoke: ``JAX_PLATFORMS=cpu UNIONML_TPU_BENCH_PRESET=tiny python
benchmarks/remote_bert/app.py`` (tiny BERT, 3 steps).
"""

import json
import os
import time

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # pre-registered TPU plugins override the env var; the config API wins
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from unionml_tpu import Dataset, Model
from unionml_tpu.models import (
    BertClassifier,
    BertConfig,
    classification_step,
    create_train_state,
)
from unionml_tpu.models.train import TrainState

dataset = Dataset(name="bert_ft_data", test_size=0.5)
model = Model(name="bert_remote_ft", dataset=dataset)

# module handle shared between init (which builds it) and trainer (which
# builds the jitted step from it); keyed per-process, exactly one config
_ctx: dict = {}


@dataset.reader
def reader(n: int = 64, seq: int = 128, tiny: int = 0) -> dict:
    rng = np.random.default_rng(0)
    vocab = 1024 if tiny else 30522
    return {
        "features": rng.integers(0, vocab, size=(n, seq)).astype(np.int32),
        "targets": rng.integers(0, 2, size=(n,)).astype(np.int32),
    }


@dataset.splitter
def splitter(data: dict, test_size: float, shuffle: bool, random_state: int):
    k = int(len(data["features"]) * (1 - test_size))
    return (
        {"features": data["features"][:k], "targets": data["targets"][:k]},
        {"features": data["features"][k:], "targets": data["targets"][k:]},
    )


@dataset.parser
def parser(data: dict, features, targets):
    return (data["features"], data["targets"])


@model.init
def init(hyperparameters: dict) -> TrainState:
    tiny = bool(hyperparameters.get("tiny", False))
    cfg = BertConfig.tiny() if tiny else BertConfig.base()
    module = BertClassifier(cfg)
    _ctx["module"] = module
    return create_train_state(
        module, jnp.zeros((1, 8), jnp.int32),
        learning_rate=hyperparameters.get("learning_rate", 2e-5),
    )


@model.trainer
def trainer(
    state: TrainState,
    features: np.ndarray,
    targets: np.ndarray,
    *,
    batch_size: int = 32,
    steps: int = 100,
    warmup: int = 10,
) -> TrainState:
    """Timed fine-tune loop (BASELINE.md methodology: warmup, >=100-step
    window on TPU, window terminated by a host readback data-dependent on
    the donated final state)."""
    ids = jnp.asarray(features[:batch_size])
    labels = jnp.asarray(targets[:batch_size])
    from benchmarks._timing import drain

    step = jax.jit(classification_step(_ctx["module"]), donate_argnums=0)
    for _ in range(warmup):
        state, metrics = step(state, (ids, labels))
    drain(state)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, (ids, labels))
    drain(state)  # param-element fence, see benchmarks/_timing.py
    dt = time.perf_counter() - t0
    _ctx["samples_per_sec"] = batch_size * steps / dt
    return state


@model.evaluator
def evaluator(state: TrainState, features: np.ndarray, targets: np.ndarray) -> float:
    # surfaces the throughput measured inside the remote trainer as the
    # execution's metric (the artifact's model-quality signal is not the
    # point of this config — the remote-lifecycle timing is)
    return float(_ctx.get("samples_per_sec", 0.0))


if __name__ == "__main__":
    tiny = os.environ.get("UNIONML_TPU_BENCH_PRESET") == "tiny"
    model.remote(project="bert-remote-bench")
    version = model.remote_deploy(app_version="r2-bench", allow_uncommitted=True)
    artifact = model.remote_train(
        app_version=version,
        hyperparameters={"tiny": tiny},
        trainer_kwargs=(
            {"batch_size": 8, "steps": 3, "warmup": 1} if tiny
            else {"batch_size": 32, "steps": 100, "warmup": 10}
        ),
        n=64,
        seq=128,
        tiny=int(tiny),
    )
    print(json.dumps({
        "metric": "bert_remote_ft_train_samples_per_sec_per_chip",
        "value": round(artifact.metrics["train"], 1),
        "unit": "samples/sec/chip",
        "lifecycle": "remote_deploy -> remote_train (LocalBackend subprocess)",
        "tiny": tiny,
    }))
