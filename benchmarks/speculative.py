"""Speculative-decoding benchmark: the MEASURED acceptance→speedup curve
(8B target + 1.5B draft).

Weights are synthetic (an 8B master tree cannot be materialized on-chip
to quantize from — see serve_latency), so organic draft/target agreement
is chance-level. Acceptance is therefore CONTROLLED with a measurement
instrument, not projected: :class:`BoostedTarget` wraps the real 8B
forward and adds ``boost * onehot(next_input_token)`` to each
non-terminal position's logits. In the verify forward the next input
token at position i IS the draft's proposal d_{i+1}, so a proposal is
accepted exactly when the target's top-logit margin over d is below
``boost`` — per-position acceptance becomes P(margin < boost), a knob
calibrated from ONE margin-distribution measurement. The verify cost is
the genuine 8B forward (the boost is one fused one-hot add on [B, k+1,
vocab]); the draft cost is the genuine 1.5B scan — so every point on
the curve is a real wall-clock measurement of the real program, with
the observed acceptance reported from the generator's own stats.

Scenarios (one JSON line each):

- plain greedy 8B decode (the baseline p50);
- speculative decode, 1.5B draft, k in {2, 4}: worst-case (acceptance
  ~= 0) latency — the overhead floor;
- the curve: k in {2, 4, 8} x target per-position acceptance in
  {25, 50, 75, 100}% — measured ms, measured acceptance, speedup;
- self-speculation (draft = target, acceptance = 100%): the round
  mechanics at full acceptance.

Usage::

    python benchmarks/speculative.py [--skip-curve]   # on the TPU
    UNIONML_TPU_BENCH_PRESET=tiny JAX_PLATFORMS=cpu python benchmarks/speculative.py
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def make_boosted_target(target_config):
    """A drop-in Llama whose logits are nudged toward the NEXT input
    token at every non-terminal position (the acceptance instrument —
    see module docstring). The nudge strength is the ``boost`` PARAM
    leaf — ``apply`` with ``{"inner": t_params, "boost": c}`` — so the
    acceptance sweep re-uses ONE compiled program per k instead of
    recompiling the 8B graph per boost value."""
    import jax
    from flax import linen as nn

    from unionml_tpu.models import Llama
    from unionml_tpu.models.llama import LlamaConfig

    class BoostedTarget(nn.Module):
        # same attribute name as Llama so make_speculative_generator's
        # `target.config` (cache geometry, vocab check) keeps working
        config: LlamaConfig

        @nn.compact
        def __call__(self, tokens, **kwargs):
            boost = self.param("boost", nn.initializers.zeros, ())
            out = Llama(self.config, name="inner")(tokens, **kwargs)
            logits, cache = out if isinstance(out, tuple) else (out, None)
            # prefill passes logit_index (one position's logits, never
            # compared to a next input) — boost only the verify shape
            if tokens.shape[1] > 1 and kwargs.get("logit_index") is None:
                nudge = boost * jax.nn.one_hot(
                    tokens[:, 1:], logits.shape[-1], dtype=logits.dtype
                )
                logits = logits.at[:, :-1, :].add(nudge)
            return (logits, cache) if cache is not None else logits

    return BoostedTarget(config=target_config)


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.models import Llama, LlamaConfig, make_generator
    from unionml_tpu.models.speculative import make_speculative_generator
    from benchmarks.serve_latency import serving_config

    tiny = os.environ.get("UNIONML_TPU_BENCH_PRESET") == "tiny" or (
        jax.default_backend() == "cpu"
    )
    prompt_len, new_tokens, reps = (8, 6, 2) if tiny else (64, 32, 10)

    if tiny:
        t_cfg = LlamaConfig.tiny(vocab_size=512)
        d_cfg = LlamaConfig.tiny(
            vocab_size=512, hidden_dim=32, num_layers=1, num_heads=2,
            num_kv_heads=1, mlp_dim=64,
        )
        tiny_toks = jnp.zeros((1, 8), jnp.int32)
        t_params = Llama(t_cfg).init(jax.random.PRNGKey(0), tiny_toks)["params"]
        d_params = Llama(d_cfg).init(jax.random.PRNGKey(1), tiny_toks)["params"]
        target, draft = Llama(t_cfg), Llama(d_cfg)
    else:
        from benchmarks.serve_latency import random_quantized_params

        t_cfg = LlamaConfig(**{**serving_config("serve_8b").__dict__, "quantized": True})
        if "--draft-small" in sys.argv:
            # ~0.3B draft: pushes the per-round draft share from ~19 ms
            # toward ~5 ms (the curve's identified lever — the 1.5B
            # draft is too large a fraction of the 8B target)
            d_cfg = LlamaConfig(
                vocab_size=128_256, hidden_dim=1024, num_layers=10,
                num_heads=16, num_kv_heads=8, mlp_dim=2816, max_len=2048,
                quantized=True,
            )
        else:
            d_cfg = LlamaConfig(
                **{**serving_config("serve_1p5b").__dict__, "quantized": True}
            )
        target, draft = Llama(t_cfg), Llama(d_cfg)
        t_params = random_quantized_params(target)
        d_params = random_quantized_params(draft)

    rng = np.random.default_rng(0)
    prompts = jnp.asarray(
        rng.integers(1, min(t_cfg.vocab_size, d_cfg.vocab_size),
                     size=(1, prompt_len)), jnp.int32,
    )

    def readback(out):
        # np.asarray per leaf, NOT block_until_ready: through the
        # tunneled backend only a data readback actually gates on the
        # remote compute (block_until_ready returns early — measured
        # 0.3 ms "8B decodes" when this used block_until_ready)
        return jax.tree_util.tree_map(np.asarray, out)

    def timed(fn, *args):
        out = readback(fn(*args))          # compile
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = readback(fn(*args))
            best = min(best, time.perf_counter() - t0)
        return best * 1e3, out

    plain = make_generator(target, max_new_tokens=new_tokens,
                           max_len=prompt_len + new_tokens)
    base_ms, _ = timed(plain, t_params, prompts)
    print(json.dumps({
        "metric": "spec_decode_baseline_ms", "value": round(base_ms, 1),
        "unit": "ms", "new_tokens": new_tokens,
    }))

    for k in (2, 4):
        spec = make_speculative_generator(
            target, draft, max_new_tokens=new_tokens, speculate_k=k,
            max_len=prompt_len + new_tokens,
        )
        worst_ms, _ = timed(spec, t_params, d_params, prompts)
        # per-round cost model from the worst case: acceptance 0 means
        # new_tokens rounds of (k draft steps + 1 verify); at acceptance
        # a, rounds shrink by (1 + a*k) emitted per round
        print(json.dumps({
            "metric": "spec_decode_worstcase_ms", "k": k,
            "value": round(worst_ms, 1), "unit": "ms",
            "overhead_vs_plain": round(worst_ms / base_ms, 2),
            "breakeven_note": (
                "acceptance a cuts rounds ~(1+a*k)x; speedup crosses 1.0 "
                f"near a ~= {round((worst_ms / base_ms - 1) / k, 2)}"
            ),
        }))

    # ---- the measured acceptance -> speedup curve -------------------- #
    if "--skip-curve" not in sys.argv:
        # calibrate the boost from ONE margin measurement: the target's
        # top-logit margin over the draft's greedy choice, sampled across
        # positions. Per-position acceptance at boost c is P(margin < c),
        # so c for acceptance p is the p-quantile of the margins.
        probe = jnp.asarray(
            rng.integers(1, min(t_cfg.vocab_size, d_cfg.vocab_size),
                         size=(4, prompt_len)), jnp.int32,
        )

        @jax.jit
        def margins(t_params, d_params, tokens):
            d_logits = draft.apply({"params": d_params}, tokens)
            proposals = jnp.argmax(d_logits, -1)
            z = target.apply({"params": t_params}, tokens)
            top = jnp.max(z, axis=-1)
            at = jnp.take_along_axis(z, proposals[..., None], axis=-1)[..., 0]
            return (top - at).ravel()

        m = np.asarray(margins(t_params, d_params, probe))
        boosts = {
            25: float(np.quantile(m, 0.25)),
            50: float(np.quantile(m, 0.50)),
            75: float(np.quantile(m, 0.75)),
            100: float(m.max()) * 1.5 + 1.0,
        }
        bt = make_boosted_target(t_cfg)
        for k in (2, 4, 8):
            spec = make_speculative_generator(
                bt, draft, max_new_tokens=new_tokens, speculate_k=k,
                max_len=prompt_len + new_tokens, with_stats=True,
            )
            for pct, c in boosts.items():
                # boost rides the param tree: ONE compile per k
                ms, (_, stats) = timed(
                    spec,
                    {"inner": t_params, "boost": jnp.float32(c)},
                    d_params, prompts,
                )
                rounds = int(np.asarray(stats["rounds"]).max())
                accepted = int(np.asarray(stats["accepted"]).sum())
                measured_acc = accepted / max(1, rounds * k)
                print(json.dumps({
                    "metric": "spec_decode_curve_ms", "k": k,
                    "target_acceptance_pct": pct,
                    "measured_acceptance_pct": round(100 * measured_acc, 1),
                    "value": round(ms, 1), "unit": "ms",
                    "rounds": rounds,
                    "speedup_vs_plain": round(base_ms / ms, 2),
                }))

    # self-speculation on the DRAFT-sized model: the 8B pair would hold
    # two 8B compute graphs at once (compile-time duplication exceeds one
    # chip's HBM); the 1.5B pair pins the same full-acceptance mechanics
    self_spec = make_speculative_generator(
        draft, draft, max_new_tokens=new_tokens, speculate_k=4,
        max_len=prompt_len + new_tokens,
    )
    plain_d = make_generator(draft, max_new_tokens=new_tokens,
                             max_len=prompt_len + new_tokens)
    base_d_ms, _ = timed(plain_d, d_params, prompts)
    self_ms, _ = timed(self_spec, d_params, d_params, prompts)
    print(json.dumps({
        "metric": "spec_decode_selfspec_ms", "k": 4,
        "value": round(self_ms, 1), "unit": "ms",
        "plain_draft_ms": round(base_d_ms, 1),
        "note": "acceptance=100% mechanics bound on the draft-sized model "
                "(draft = target: no saving expected)",
    }))


if __name__ == "__main__":
    main()
