"""Speculative-decoding mechanics benchmark (8B target + 1.5B draft).

Weights here are synthetic (an 8B master tree cannot be materialized
on-chip to quantize from — see serve_latency), so DRAFT/TARGET
agreement is chance-level and measured acceptance is ~0: this bench
therefore measures the MECHANICS — the worst-case overhead of
speculation and the per-component costs — and derives the
speedup-vs-acceptance curve those costs imply for trained checkpoints
(typical published acceptance at k=4 is ~60-80%).

Scenarios (one JSON line each):

- plain greedy 8B decode (the baseline p50);
- speculative decode, 1.5B draft, k in {2, 4}: worst-case (acceptance
  ~= 0) latency;
- self-speculation (draft = target, acceptance = 100%): the round
  mechanics at full acceptance — not a speedup (the draft costs as
  much as the target), but it pins the best-case round count.

Usage::

    python benchmarks/speculative.py            # on the TPU
    UNIONML_TPU_BENCH_PRESET=tiny JAX_PLATFORMS=cpu python benchmarks/speculative.py
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.models import Llama, LlamaConfig, make_generator
    from unionml_tpu.models.speculative import make_speculative_generator
    from benchmarks.serve_latency import serving_config

    tiny = os.environ.get("UNIONML_TPU_BENCH_PRESET") == "tiny" or (
        jax.default_backend() == "cpu"
    )
    prompt_len, new_tokens, reps = (8, 6, 2) if tiny else (64, 32, 10)

    if tiny:
        t_cfg = LlamaConfig.tiny(vocab_size=512)
        d_cfg = LlamaConfig.tiny(
            vocab_size=512, hidden_dim=32, num_layers=1, num_heads=2,
            num_kv_heads=1, mlp_dim=64,
        )
        tiny_toks = jnp.zeros((1, 8), jnp.int32)
        t_params = Llama(t_cfg).init(jax.random.PRNGKey(0), tiny_toks)["params"]
        d_params = Llama(d_cfg).init(jax.random.PRNGKey(1), tiny_toks)["params"]
        target, draft = Llama(t_cfg), Llama(d_cfg)
    else:
        from benchmarks.serve_latency import random_quantized_params

        t_cfg = LlamaConfig(**{**serving_config("serve_8b").__dict__, "quantized": True})
        d_cfg = LlamaConfig(**{**serving_config("serve_1p5b").__dict__, "quantized": True})
        target, draft = Llama(t_cfg), Llama(d_cfg)
        t_params = random_quantized_params(target)
        d_params = random_quantized_params(draft)

    rng = np.random.default_rng(0)
    prompts = jnp.asarray(
        rng.integers(1, min(t_cfg.vocab_size, d_cfg.vocab_size),
                     size=(1, prompt_len)), jnp.int32,
    )

    def timed(fn, *args):
        out = fn(*args)          # compile
        np.asarray(out)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn(*args)
            np.asarray(out)      # data-dependent readback gates the tunnel
            best = min(best, time.perf_counter() - t0)
        return best * 1e3

    plain = make_generator(target, max_new_tokens=new_tokens,
                           max_len=prompt_len + new_tokens)
    base_ms = timed(plain, t_params, prompts)
    print(json.dumps({
        "metric": "spec_decode_baseline_ms", "value": round(base_ms, 1),
        "unit": "ms", "new_tokens": new_tokens,
    }))

    for k in (2, 4):
        spec = make_speculative_generator(
            target, draft, max_new_tokens=new_tokens, speculate_k=k,
            max_len=prompt_len + new_tokens,
        )
        worst_ms = timed(spec, t_params, d_params, prompts)
        # per-round cost model from the worst case: acceptance 0 means
        # new_tokens rounds of (k draft steps + 1 verify); at acceptance
        # a, rounds shrink by (1 + a*k) emitted per round
        print(json.dumps({
            "metric": "spec_decode_worstcase_ms", "k": k,
            "value": round(worst_ms, 1), "unit": "ms",
            "overhead_vs_plain": round(worst_ms / base_ms, 2),
            "breakeven_note": (
                "acceptance a cuts rounds ~(1+a*k)x; speedup crosses 1.0 "
                f"near a ~= {round((worst_ms / base_ms - 1) / k, 2)}"
            ),
        }))

    # self-speculation on the DRAFT-sized model: the 8B pair would hold
    # two 8B compute graphs at once (compile-time duplication exceeds one
    # chip's HBM); the 1.5B pair pins the same full-acceptance mechanics
    self_spec = make_speculative_generator(
        draft, draft, max_new_tokens=new_tokens, speculate_k=4,
        max_len=prompt_len + new_tokens,
    )
    plain_d = make_generator(draft, max_new_tokens=new_tokens,
                             max_len=prompt_len + new_tokens)
    base_d_ms = timed(plain_d, d_params, prompts)
    self_ms = timed(self_spec, d_params, d_params, prompts)
    print(json.dumps({
        "metric": "spec_decode_selfspec_ms", "k": 4,
        "value": round(self_ms, 1), "unit": "ms",
        "plain_draft_ms": round(base_d_ms, 1),
        "note": "acceptance=100% mechanics bound on the draft-sized model "
                "(draft = target: no saving expected)",
    }))


if __name__ == "__main__":
    main()
