"""Shared timing discipline for every benchmark script (BASELINE.md
"timing methodology" + "scalar-readback hazard").

Through the tunneled TPU backend, neither ``jax.block_until_ready`` nor
a scalar METRIC readback (``float(metrics["loss"])``) actually gates on
the enqueued work — a loss-drained warmup under-reported BERT-base by
~30% in round 1. The only trustworthy fence is reading a post-update
PARAM element, which chains through every donated training step.
"""

from __future__ import annotations


def drain(state) -> float:
    """Fence: block until the step chain producing ``state`` is done."""
    import jax

    return float(jax.tree_util.tree_leaves(state.params)[0].ravel()[0])
