"""Loopback probe: the engine's co-located claim, measured.

``serving/engine.py`` argues the continuous-batching engine beats the
full-batch micro-batcher when the host↔device round trip is small
relative to a decode chunk (on the tunneled benching link RTT ~119 ms
dwarfs tiny-model chunks, so the batcher wins closed-loop p50 and
auto-mode picks it — BASELINE.md rounds 3-4). This probe runs the SAME
tiny preset on the in-process CPU backend, where the round trip truly
is ~0 — the co-located regime — and measures:

1. the auto-rule decision (expected: it FLIPS to "engine");
2. closed-loop p50/p95 of engine vs batcher under staggered arrivals.

Staggered (not barrier-aligned) arrivals are the point: clients that
arrive mid-batch wait out the batcher's whole in-flight generate, while
the engine admits them at the next chunk boundary.

Prints one JSON line per result (BASELINE.md round-5 evidence).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")  # the co-located regime
    import jax.numpy as jnp
    import numpy as np

    from benchmarks.serve_latency import serving_config
    from unionml_tpu.models import Llama, make_lm_predictor, quantize_params
    from unionml_tpu.models.quantization import LLAMA_QUANT_PATTERNS
    from unionml_tpu.serving.auto import choose_serving_mode
    from unionml_tpu.serving.engine import DecodeEngine

    cfg0 = serving_config("tiny")
    from unionml_tpu.models import LlamaConfig

    cfg = LlamaConfig(**{**cfg0.__dict__, "quantized": True})
    module = Llama(cfg)
    fp = Llama(cfg0).init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    qparams = quantize_params(fp, LLAMA_QUANT_PATTERNS)

    n_clients, reqs_per_client, prompt_len, new_tokens = 4, 6, 16, 32
    rng = np.random.default_rng(0)
    prompts = rng.integers(1, cfg.vocab_size, size=(n_clients, prompt_len))

    decision = choose_serving_mode(module, qparams, chunk_steps=8)
    print(json.dumps({"metric": "loopback_auto_decision", **decision}), flush=True)

    def closed_loop(predict) -> dict:
        lat = []
        lock = threading.Lock()

        def client(i):
            # staggered arrivals: offsets are where chunk-boundary joins
            # beat the batcher's full-batch barrier
            time.sleep(0.05 * i)
            for _ in range(reqs_per_client):
                t0 = time.perf_counter()
                predict([prompts[i].tolist()])
                with lock:
                    lat.append(time.perf_counter() - t0)

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        from unionml_tpu.serving._stats import percentile_summary

        # shared nearest-rank formula (int(0.95*n) indexed the MAXIMUM
        # for small windows — the bias _stats.percentile_summary fixes)
        s = percentile_summary([v * 1e3 for v in lat])
        return {"p50_ms": s["p50"], "p95_ms": s["p95"], "n": s["n"]}

    # --- engine ---
    engine = DecodeEngine(
        module, slots=n_clients, max_new_tokens=new_tokens,
        prompt_buckets=(prompt_len,), chunk_steps=8, pipeline_depth=2,
    )
    engine.warmup(qparams)
    closed_loop(lambda p: engine.generate(qparams, p))  # warm the path
    engine.reset_stats()
    eng = closed_loop(lambda p: engine.generate(qparams, p))
    engine.close()
    print(json.dumps({"metric": "loopback_engine_closed", **eng}), flush=True)

    # --- batcher (full-batch predictor behind a micro-batching queue) ---
    from unionml_tpu.serving.batcher import MicroBatcher

    predict = make_lm_predictor(
        module, max_new_tokens=new_tokens, bucket_lens=(prompt_len,),
    )
    predict.warmup(qparams, max_batch=n_clients)
    batcher = MicroBatcher(
        lambda feats: predict(qparams, feats), max_batch_size=n_clients,
        max_wait_ms=5.0, row_lists=True,
    )
    closed_loop(lambda p: batcher.submit(p[0]))  # warm
    bat = closed_loop(lambda p: batcher.submit(p[0]))
    batcher.close()
    print(json.dumps({"metric": "loopback_batcher_closed", **bat}), flush=True)

    print(json.dumps({
        "metric": "loopback_verdict",
        "auto_mode": decision["mode"],
        "engine_p50_ms": eng["p50_ms"],
        "batcher_p50_ms": bat["p50_ms"],
        "engine_wins_p50": eng["p50_ms"] <= bat["p50_ms"],
    }), flush=True)


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    main()
