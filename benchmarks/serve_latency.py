"""Serving-latency benchmark: Llama generation p50/p95 (BASELINE.md).

Reproduces the BASELINE.md serving rows: jitted prefill + scan decode
via :func:`unionml_tpu.models.make_generator` on a ~1.5B-param Llama-3
geometry (the largest that fits one v5e chip in bf16; the 8B config
needs the tensor-parallel path). Prints one JSON line per
(quantized, batch) combination.

Usage::

    python benchmarks/serve_latency.py [--batches 1 8] [--trials 20]
    UNIONML_TPU_BENCH_PRESET=tiny python benchmarks/serve_latency.py  # CPU smoke
    UNIONML_TPU_BENCH_PRESET=serve_prefix_cache python benchmarks/serve_latency.py
    # ^ automatic prefix KV-cache: shared-prefix stream, cache on vs off
    UNIONML_TPU_BENCH_PRESET=serve_overload python benchmarks/serve_latency.py
    # ^ admission control under saturation: shed rate + accepted p99 on
    #   an over-admitted stream, and recovery time after an injected
    #   device fault (docs/robustness.md)
    UNIONML_TPU_BENCH_PRESET=serve_introspection python benchmarks/serve_latency.py
    # ^ program introspection: instrumentation-on vs -off wall delta
    #   with token parity asserted, plus the decode program's measured
    #   flops / recompiles / MFU (docs/observability.md)
    UNIONML_TPU_BENCH_PRESET=serve_tracing python benchmarks/serve_latency.py
    # ^ distributed tracing: W3C traceparent propagation + OTLP export
    #   (against the in-process collector stub) on vs off — token
    #   parity asserted, per-request p50/p99 overhead delta reported
    #   (docs/observability.md "Distributed tracing & SLOs")
    UNIONML_TPU_BENCH_PRESET=serve_paged python benchmarks/serve_latency.py
    # ^ paged KV attention: contiguous vs block-paged device cache at a
    #   FIXED HBM byte budget under a long-tail prompt mix — effective
    #   max batch ratio (target >= 1.5x), decode tokens/s at equal
    #   batch, token parity asserted (docs/performance.md)
    UNIONML_TPU_BENCH_PRESET=serve_usage python benchmarks/serve_latency.py
    # ^ per-tenant usage metering: attribution identity (per-tenant
    #   attributed device-seconds + tokens explain >= 95% of engine
    #   totals under a mixed 3-tenant stream), exported tenant-label
    #   cardinality <= top_k + 1 under a 40-distinct-tenant burst, and
    #   ledger-on vs -off p99 overhead <= 2% at token parity
    #   (docs/observability.md "Usage metering & cost attribution")
    UNIONML_TPU_BENCH_PRESET=serve_preempt python benchmarks/serve_latency.py
    # ^ preemptive priority scheduling: a low-priority bulk tenant
    #   floods the paged KV pool while a high-priority tenant streams
    #   — asserts premium p99 holds within 1.5x of its unloaded
    #   baseline, preempted streams resume with exact token parity,
    #   and zero caller-visible failures (docs/robustness.md
    #   "Preemption & fairness")
    UNIONML_TPU_BENCH_PRESET=serve_router python benchmarks/serve_latency.py
    # ^ fleet router (cluster front door): 3 engine replicas under a
    #   concurrent stream with a mid-run replica KILL (OOM-shaped
    #   device fault) plus a drain→rejoin cycle — asserts ZERO
    #   caller-visible failures with per-request token parity, retry
    #   amplification within the fleet retry budget; then a 1-replica
    #   passthrough leg asserting <= 2% p99 overhead vs the direct
    #   engine (docs/robustness.md "Fleet robustness")
    UNIONML_TPU_BENCH_PRESET=serve_autoscale python benchmarks/serve_latency.py
    # ^ SLO-driven autoscaling (the self-operating fleet): a
    #   burn-inducing flood on a 2-replica fleet triggers a scale-out
    #   within the SLO fast window, warm-joined from a donor's hot
    #   prefix blocks (>= 1 warm hit on the joiner's first request
    #   asserted); a mid-run replica kill is reaped and replaced
    #   automatically; the load drop scales the fleet back to
    #   baseline — zero caller-visible failures and exact token
    #   parity vs the solo oracle throughout (docs/robustness.md
    #   "Autoscaling & self-healing")
    UNIONML_TPU_BENCH_PRESET=serve_disagg python benchmarks/serve_latency.py
    # ^ disaggregated prefill/decode serving: colocated vs phase-split
    #   fleets of identical size under mixed long/short-prompt traffic
    #   — asserts the disaggregated short-prompt TTFT p99 beats
    #   colocated with decode tokens/s no worse, all completions
    #   bit-identical to the colocated solo oracle, 0 caller-visible
    #   failures; then a chaos leg killing the prefill replica
    #   mid-handoff with lease/pool refcounts back to baseline
    #   (docs/serving.md "Disaggregated serving")
    UNIONML_TPU_BENCH_PRESET=serve_fleet_obs python benchmarks/serve_latency.py
    # ^ fleet observability plane: a 3-replica fleet under load with
    #   cross-hop trace stitching ON and a concurrent federated
    #   /metrics scraper — zero caller-visible failures, exact token
    #   parity, every replica labeled in the one-scrape body, the
    #   probe request's stitched timeline complete; then per-request
    #   paired plane-on/off legs asserting <= 2% p99 overhead at
    #   bit-identical tokens (docs/observability.md "Fleet
    #   observability")
    UNIONML_TPU_BENCH_PRESET=serve_perf python benchmarks/serve_latency.py
    # ^ serving goodput plane: a single-replica router fleet under
    #   load with the plane ON — zero caller-visible failures, exact
    #   token parity, fleet-merged /debug/goodput sane and the
    #   per-token ITL histogram populated; then per-request paired
    #   plane-on/off legs on the SAME engine (the engine.perf setter
    #   seam) asserting <= 1% pooled-p99 overhead at bit-identical
    #   tokens, with one tail probe per sweep resolved /debug/tail →
    #   /debug/trace (docs/observability.md "Serving goodput & tail
    #   attribution")
    UNIONML_TPU_BENCH_PRESET=serve_rollout python benchmarks/serve_latency.py
    # ^ zero-downtime model lifecycle: a 2-engine fleet under flood
    #   has a bad version rolled forward and auto-rolled back on its
    #   shadow parity regression, then a clean version baked and
    #   promoted through rolling drain/bind/rejoin — per sweep, three
    #   sweeps; 0 caller-visible failures, exact token parity on the
    #   live path, lifecycle-churn TTFT p99 within 2x of the
    #   steady-state baseline measured by the same min-over-rounds /
    #   unrounded-nearest-rank / median-of-three estimator
    #   (docs/robustness.md "Rollouts & rollback")
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
from functools import partial
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def serving_config(preset: str):
    from unionml_tpu.models import LlamaConfig

    if preset == "tiny":
        return LlamaConfig.tiny(vocab_size=256)
    if preset == "serve_8b":
        # the BASELINE.json config #5 model: full Llama-3-8B geometry.
        # bf16 (16 GB) exceeds one v5e chip's HBM; int8 weights (~8.6 GB)
        # fit with room for bucketed KV caches -> int8-only legs.
        return LlamaConfig.llama3_8b()
    if preset == "serve_1p5b_w4":
        # packed-int4 at the 1.5B scale: the second confirmation point
        # for the ops/int4_matmul.py decode kernel
        base = serving_config("serve_1p5b")
        return LlamaConfig(**{**base.__dict__, "weight_bits": 4})
    if preset == "serve_8b_w4":
        # packed-int4 weights (~4.3 GB): the ops/int4_matmul.py Pallas
        # decode path — halves the weight traffic that bounds 8B decode
        return LlamaConfig(**{
            **LlamaConfig.llama3_8b().__dict__, "weight_bits": 4,
        })
    if preset == "serve_moe":
        # ~1.1B-total-param 8-expert top-2 MoE (~0.4B active per token)
        return LlamaConfig(
            vocab_size=128_256, hidden_dim=1024, num_layers=12, num_heads=16,
            num_kv_heads=8, mlp_dim=2816, max_len=2048,
            num_experts=8, num_selected=2,
        )
    # ~1.5B params: Llama-3 geometry scaled to one v5e chip (bf16 ~3 GB)
    return LlamaConfig(
        vocab_size=128_256, hidden_dim=2048, num_layers=20, num_heads=16,
        num_kv_heads=8, mlp_dim=5632, max_len=2048,
    )


def random_quantized_params(qmodule, seed: int = 0):
    """Synthetic weights with the quantized module's exact tree/dtypes.

    The 8B bf16 master tree (16 GB) cannot be materialized on one v5e
    chip to run ``quantize_params`` over, and decode latency is
    weight-VALUE-independent (HBM traffic + MXU work depend only on
    shapes/dtypes — TPUs have no denormal slow paths), so the 8B bench
    fills each leaf directly on device: random int8 kernels, lecun-scaled
    fp32 scales, N(0, 0.02) embeddings, ones for norm gains. Leaves are
    created one at a time — peak transient memory is one leaf's int32
    sample buffer, never a second full tree.
    """
    import jax
    import jax.numpy as jnp

    shapes = jax.eval_shape(
        qmodule.init, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    flat, treedef = jax.tree_util.tree_flatten_with_path(shapes)
    # leaf-name -> sibling-names map: a "scale" leaf is quant metadata only
    # next to its int8 kernel (RMSNorm gains are ALSO named "scale" and
    # must get ones, not the tiny dequant constant)
    sibling_names = {}
    for path, _ in flat:
        parent = tuple(p.key if hasattr(p, "key") else str(p) for p in path[:-1])
        sibling_names.setdefault(parent, set()).add(
            path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        )

    @partial(jax.jit, static_argnums=(1,))
    def int8_leaf(key, shape):
        return jax.random.randint(key, shape, -127, 128, jnp.int32).astype(jnp.int8)

    @partial(jax.jit, static_argnums=(1, 2))
    def embed_leaf(key, shape, dtype):
        return (0.02 * jax.random.normal(key, shape)).astype(dtype)

    key = jax.random.PRNGKey(seed)
    leaves = []
    for path, s in flat:
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        parent = tuple(p.key if hasattr(p, "key") else str(p) for p in path[:-1])
        siblings = sibling_names[parent]
        is_quant_scale = (
            name in ("scale", "scale_g")
            and ("kernel_q" in siblings or "kernel_p" in siblings)
        ) or (
            name.endswith("_scale") and f"{name[: -len('_scale')]}_q" in siblings
        )
        key, sub = jax.random.split(key)
        if s.dtype == jnp.int8:
            leaves.append(int8_leaf(sub, s.shape))
        elif is_quant_scale:
            # uniform int8 in [-127,127] has std ~73; scale so the
            # effective weight std lands near lecun 1/sqrt(K)
            k_in = qmodule.config.hidden_dim
            leaves.append(
                jnp.full(s.shape, 1.0 / (73.0 * math.sqrt(k_in)), jnp.float32)
            )
        elif name == "embedding":
            leaves.append(embed_leaf(sub, s.shape, s.dtype))
        else:
            leaves.append(jnp.ones(s.shape, s.dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batches", type=int, nargs="+", default=[1, 8])
    parser.add_argument("--trials", type=int, default=20)
    parser.add_argument("--prompt-len", type=int, default=64)
    parser.add_argument("--new-tokens", type=int, default=32)
    parser.add_argument(
        "--prefill-impl", choices=("cached", "flash"), default="cached",
        help="flash = Pallas monolithic prefill (the long-prompt lever; "
        "BASELINE.md round 5: 1.68x at 1.5B x 4k)",
    )
    parser.add_argument(
        "--prefill-chunk", type=int, default=None,
        help="chunked cached prefill (bounds the [B,H,chunk,max_len] "
        "score buffer; the pre-flash long-prompt path and the flash A/B "
        "baseline)",
    )
    parser.add_argument(
        "--kv-quant", action="store_true",
        help="int8 KV cache (composes with either prefill impl)",
    )
    args = parser.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.models import (
        LLAMA_QUANT_PATTERNS,
        LlamaConfig,
        Llama,
        make_generator,
        quantize_params,
        serving_params,
    )

    backend = jax.default_backend()
    preset = os.environ.get(
        "UNIONML_TPU_BENCH_PRESET", "tiny" if backend == "cpu" else "serve_1p5b"
    )
    if preset == "tiny":
        args.trials = min(args.trials, 3)
    if args.prefill_impl == "flash" and args.prefill_chunk:
        # chunking makes the tail call partial, so generate() never takes
        # the flash path — measuring this silently would record a chunked
        # number as a flash datapoint
        parser.error("--prefill-impl flash is mutually exclusive with "
                     "--prefill-chunk (a chunked prefill is never a full "
                     "prefill; see docs/serving.md)")
    cfg = serving_config(preset)
    overrides = {}
    if args.prefill_impl != "cached":
        overrides["prefill_impl"] = args.prefill_impl
    if args.kv_quant:
        overrides["kv_quant"] = True
    if overrides:
        import dataclasses

        cfg = dataclasses.replace(cfg, **overrides)
    rng = np.random.default_rng(0)

    if preset.startswith("serve_8b"):
        # bf16 8B exceeds single-chip HBM: quantized-only, synthetic weights
        legs = (True,)
        module, params, fp_params = None, None, None
    elif preset.endswith("_w4"):
        # w4 presets measure the quantized leg only (the fp leg is the
        # base preset's, already recorded)
        legs = (True,)
        module, params = None, None
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        fp_params = jax.jit(Llama(cfg).init)(jax.random.PRNGKey(0), tokens0)["params"]
    else:
        legs = (False, True)
        module = Llama(cfg)
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        fp_params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
        # serving residency: one-time bf16 cast (decode re-reads weights per token)
        params = serving_params(fp_params)

    for quantized in legs:
        if quantized:
            qcfg = LlamaConfig(**{**cfg.__dict__, "quantized": True})
            qmodule = Llama(qcfg)
            if preset.startswith("serve_8b"):
                qparams = random_quantized_params(qmodule)
            else:
                # quantize from the fp32 masters (the production path), not
                # the bf16 serving copy: scales from bf16 weights double-round
                qparams = quantize_params(
                    fp_params, LLAMA_QUANT_PATTERNS,
                    bits=getattr(cfg, "weight_bits", 8),
                )
            run_module, run_params = qmodule, qparams
        else:
            run_module, run_params = module, params
        # cache sized to the request (make_lm_predictor does this per bucket)
        generate = make_generator(
            run_module, max_new_tokens=args.new_tokens,
            max_len=args.prompt_len + args.new_tokens,
            prefill_chunk=args.prefill_chunk,
        )
        for batch in args.batches:
            prompt = jnp.asarray(
                rng.integers(1, cfg.vocab_size, size=(batch, args.prompt_len)),
                jnp.int32,
            )
            # warmup/compile
            out = generate(run_params, prompt)
            _ = np.asarray(out)
            lat = []
            for _ in range(args.trials):
                t0 = time.perf_counter()
                out = generate(run_params, prompt)
                _ = np.asarray(out)  # host readback = end of request
                lat.append((time.perf_counter() - t0) * 1e3)
            from unionml_tpu.serving._stats import percentile_summary

            s = percentile_summary(lat)  # shared nearest-rank formula
            p50, p95 = s["p50"], s["p95"]
            toks = batch * args.new_tokens / (p50 / 1e3)
            print(json.dumps({
                "metric": f"{preset}_generate_p50_ms",
                "quantized": quantized,
                "batch": batch,
                "prompt_len": args.prompt_len,
                "new_tokens": args.new_tokens,
                "prefill_impl": args.prefill_impl,
                "prefill_chunk": args.prefill_chunk,
                "kv_quant": bool(cfg.kv_quant),
                "value": round(p50, 1),
                "p95_ms": round(p95, 1),
                "tokens_per_sec": round(toks, 1),
                "unit": "ms",
            }))


def kv_cache_legs() -> None:
    """Long-context decode: bf16 vs int8 KV cache
    (``UNIONML_TPU_BENCH_KV=1``, composes with the preset env var).

    Decode streams weights AND the filled cache every step; at serving's
    short prompts the cache is noise next to the weights, but at long
    prompts it rivals them (1.5B int8 weights ~1.5 GB vs ~0.75 GB bf16
    cache at batch 8 x 1152 ctx). ``kv_quant`` halves the cache bytes —
    both the per-step HBM traffic share and the resident footprint that
    caps engine slot counts.
    """
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.models import LlamaConfig, Llama, make_generator

    backend = jax.default_backend()
    preset = os.environ.get(
        "UNIONML_TPU_BENCH_PRESET", "tiny" if backend == "cpu" else "serve_1p5b"
    )
    cfg = serving_config(preset)
    trials = 3 if preset == "tiny" else 20
    if preset == "tiny":
        prompt_len, new_tokens, batch = 16, 4, 2
    elif preset == "serve_8b":
        # the capability-unlock config: 8B x 8k context x batch 8. The
        # bf16 cache alone is 32L x 2 x 8 x 8192 x 8 x 128 x 2B = 8.6 GB
        # — plus the 8.6 GB int8 weights it EXCEEDS one v5e's HBM (the
        # bf16 leg is expected to OOM and is reported as such); the int8
        # cache (4.4 GB) fits with ~3 GB to spare.
        prompt_len, new_tokens, batch, trials = 8064, 128, 8, 5
    else:
        prompt_len, new_tokens, batch = 1024, 128, 8
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(1, cfg.vocab_size, size=(batch, prompt_len)), jnp.int32
    )
    base = LlamaConfig(**{**cfg.__dict__, "quantized": True})
    # params are identical for both legs (kv_quant changes only the cache)
    # — build ONE tree; a per-leg copy would transiently double-hold the
    # weights (17 GB at the 8B preset on a 16 GB chip)
    qparams = random_quantized_params(Llama(base))
    for kv_quant in (False, True):
        qcfg = LlamaConfig(**{**base.__dict__, "kv_quant": kv_quant})
        qmodule = Llama(qcfg)
        generate = make_generator(
            qmodule, max_new_tokens=new_tokens,
            max_len=prompt_len + new_tokens,
            # 8k prefill needs both long-context knobs: chunked prefill
            # bounds the [B, H, chunk, total] score buffer (~1 GB at 128)
            # and the last-position-only head avoids [B, S, vocab] logits
            prefill_chunk=128 if prompt_len >= 4096 else None,
        )
        cache_mb = (
            cfg.num_layers * 2 * batch * (prompt_len + new_tokens)
            * cfg.num_kv_heads * cfg.head_dim
            * ((1 + 4 / cfg.head_dim) if kv_quant else 2) / 1e6
        )
        metric = f"{preset}_longctx_kv_{'int8' if kv_quant else 'bf16'}_p50_ms"
        try:
            _ = np.asarray(generate(qparams, prompt))  # compile
        except jax.errors.JaxRuntimeError as e:
            # only genuine memory exhaustion is the expected "bf16 cache
            # doesn't fit" datapoint; anything else is a regression and
            # must fail the run, not masquerade as the OOM result
            if not any(
                marker in str(e)
                for marker in ("Ran out of memory", "RESOURCE_EXHAUSTED",
                               "Exceeded hbm capacity")
            ):
                raise
            print(json.dumps({
                "metric": metric,
                "batch": batch, "prompt_len": prompt_len,
                "new_tokens": new_tokens, "cache_mb": round(cache_mb, 1),
                "value": None, "oom": True,
                "error": f"{type(e).__name__}: {str(e)[:160]}",
                "unit": "ms",
            }))
            continue
        lat = []
        for _ in range(trials):
            t0 = time.perf_counter()
            _ = np.asarray(generate(qparams, prompt))
            lat.append((time.perf_counter() - t0) * 1e3)
        lat.sort()
        p50 = lat[len(lat) // 2]
        print(json.dumps({
            "metric": metric,
            "batch": batch, "prompt_len": prompt_len, "new_tokens": new_tokens,
            "cache_mb": round(cache_mb, 1),
            "value": round(p50, 1),
            "tokens_per_sec": round(batch * new_tokens / (p50 / 1e3), 1),
            "unit": "ms",
        }))


def prefix_cache_legs() -> None:
    """Shared-prefix (system prompt) serving: cached vs naive
    (``UNIONML_TPU_BENCH_PREFIX=1``, composes with the preset env var).

    Per-request prefill work is proportional to prompt length; a system
    prompt shared by every request multiplies it for no information
    gain. ``make_lm_predictor(system_prefix=...)`` prefills the prefix
    once per weights and broadcasts its KV rows, so requests pay only
    their own suffix.
    """
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from unionml_tpu.models import Llama, LlamaConfig, make_lm_predictor

    backend = jax.default_backend()
    preset = os.environ.get(
        "UNIONML_TPU_BENCH_PRESET", "tiny" if backend == "cpu" else "serve_1p5b"
    )
    cfg = serving_config(preset)
    trials = 3 if preset == "tiny" else 20
    prefix_len, prompt_len, new_tokens, batch = (
        (8, 4, 4, 2) if preset == "tiny" else (512, 64, 32, 8)
    )
    rng = np.random.default_rng(0)
    prefix = rng.integers(1, cfg.vocab_size, prefix_len).tolist()
    prompts = [
        rng.integers(1, cfg.vocab_size, prompt_len).tolist() for _ in range(batch)
    ]
    base = LlamaConfig(**{**cfg.__dict__, "quantized": True})
    qmodule = Llama(base)
    qparams = random_quantized_params(qmodule)

    for cached in (False, True):
        if cached:
            pred = make_lm_predictor(
                qmodule, max_new_tokens=new_tokens,
                bucket_lens=(prompt_len,), max_len=cfg.max_len,
                system_prefix=prefix,
            )
            reqs = prompts
        else:
            pred = make_lm_predictor(
                qmodule, max_new_tokens=new_tokens,
                bucket_lens=(prefix_len + prompt_len,), max_len=cfg.max_len,
            )
            reqs = [prefix + p for p in prompts]
        pred(qparams, reqs)  # compile (+ prefix prefill when cached)
        lat = []
        for _ in range(trials):
            t0 = time.perf_counter()
            pred(qparams, reqs)
            lat.append((time.perf_counter() - t0) * 1e3)
        lat.sort()
        p50 = lat[len(lat) // 2]
        print(json.dumps({
            "metric": f"{preset}_prefix_{'cached' if cached else 'naive'}_p50_ms",
            "batch": batch, "prefix_len": prefix_len, "prompt_len": prompt_len,
            "new_tokens": new_tokens,
            "value": round(p50, 1),
            "unit": "ms",
        }))


def prefix_cache_engine_leg() -> None:
    """Automatic prefix KV-cache under a shared-prefix request stream
    (``UNIONML_TPU_BENCH_PRESET=serve_prefix_cache``).

    The workload RadixAttention/vLLM prefix caching exist for: a stream
    of prompts where 75% share one long system-prompt-style prefix
    (64 prompts x 512 shared tokens on an accelerator; a scaled-down
    16 x 32 smoke on CPU). Runs the SAME stream through a DecodeEngine
    with the cache off and on, asserts the produced tokens are
    bit-identical, and reports hit rate, prefill-tokens-saved, and the
    TTFT delta — the prefill work the cache deleted, as a latency
    number.
    """
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.models import Llama, LlamaConfig
    from unionml_tpu.serving.engine import DecodeEngine
    from unionml_tpu.serving.prefix_cache import RadixPrefixCache

    backend = jax.default_backend()
    if backend == "cpu":
        cfg = serving_config("tiny")
        module = Llama(cfg)
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
        n_req, prefix_len, suffix_len, new_tokens = 16, 32, 8, 8
        bucket, slots, chunk_steps = 48, 4, 4
    else:
        cfg = serving_config("serve_1p5b")
        qcfg = LlamaConfig(**{**cfg.__dict__, "quantized": True})
        module = Llama(qcfg)
        params = random_quantized_params(module)
        n_req, prefix_len, suffix_len, new_tokens = 64, 512, 64, 32
        bucket, slots, chunk_steps = 640, 8, 8
    rng = np.random.default_rng(0)
    prefix = rng.integers(1, cfg.vocab_size, prefix_len).tolist()
    prompts = []
    for i in range(n_req):
        if i % 4 < 3:  # 75% share the prefix, unique suffixes
            prompts.append(
                prefix + rng.integers(1, cfg.vocab_size, suffix_len).tolist()
            )
        else:          # 25% fully distinct, same total length
            prompts.append(
                rng.integers(1, cfg.vocab_size, prefix_len + suffix_len).tolist()
            )
    results = {}
    for cached in (False, True):
        engine = DecodeEngine(
            module, slots=slots, max_new_tokens=new_tokens,
            prompt_buckets=(bucket,), chunk_steps=chunk_steps,
            prefix_cache=RadixPrefixCache() if cached else None,
        )
        try:
            engine.warmup(params)
            if cached:
                # seed request: the stream measures steady-state reuse,
                # not the first-ever prefix computation
                engine.generate(params, [prompts[0]])
            engine.reset_stats()
            t0 = time.perf_counter()
            outs = engine.generate(params, prompts)
            wall_ms = (time.perf_counter() - t0) * 1e3
            stats = engine.stats()
            results[cached] = (outs, stats, wall_ms)
        finally:
            engine.close()
    assert results[False][0] == results[True][0], (
        "prefix cache changed produced tokens — parity violation"
    )
    off_ttft = results[False][1].get("ttft_ms", {})
    on_ttft = results[True][1].get("ttft_ms", {})
    cache_stats = results[True][1]["prefix_cache"]
    for cached in (False, True):
        _, stats, wall_ms = results[cached]
        ttft = stats.get("ttft_ms", {})
        print(json.dumps({
            "metric": "serve_prefix_cache_ttft_p50_ms",
            "cached": cached,
            "requests": n_req,
            "prefix_len": prefix_len,
            "suffix_len": suffix_len,
            "new_tokens": new_tokens,
            "value": round(ttft.get("p50", 0.0), 1),
            "p95_ms": round(ttft.get("p95", 0.0), 1),
            "wall_ms": round(wall_ms, 1),
            "unit": "ms",
        }))
    print(json.dumps({
        "metric": "serve_prefix_cache_summary",
        "hit_rate": cache_stats["hit_rate"],
        "prefill_tokens_saved": cache_stats["prefill_tokens_saved"],
        "ttft_p50_delta_ms": round(
            off_ttft.get("p50", 0.0) - on_ttft.get("p50", 0.0), 1
        ),
        "tokens_identical": True,
        "unit": "ms",
    }))


def introspection_leg() -> None:
    """Program-introspection overhead + hardware-truth report
    (``UNIONML_TPU_BENCH_PRESET=serve_introspection``).

    Runs the SAME request stream through a DecodeEngine with
    introspection (cost-analysis tracker + MFU gauges + flight
    recorder) OFF and ON, asserts the produced tokens are
    bit-identical, and reports the wall-clock overhead delta — the
    number that keeps the "introspection is off the steady-state hot
    path" claim honest — plus the decode program's measured flops,
    recompile count, and MFU/roofline ratios.
    """
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu import telemetry
    from unionml_tpu.models import Llama, LlamaConfig
    from unionml_tpu.serving.engine import DecodeEngine

    backend = jax.default_backend()
    if backend == "cpu":
        cfg = serving_config("tiny")
        module = Llama(cfg)
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
        n_req, new_tokens, bucket, slots, chunk_steps = 24, 8, 16, 4, 4
    else:
        cfg = serving_config("serve_1p5b")
        qcfg = LlamaConfig(**{**cfg.__dict__, "quantized": True})
        module = Llama(qcfg)
        params = random_quantized_params(module)
        n_req, new_tokens, bucket, slots, chunk_steps = 128, 32, 64, 8, 8
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, bucket // 2).tolist()
        for _ in range(n_req)
    ]
    results = {}
    for introspect in (False, True):
        engine = DecodeEngine(
            module, slots=slots, max_new_tokens=new_tokens,
            prompt_buckets=(bucket,), chunk_steps=chunk_steps,
            introspect=introspect,
            # isolated sinks: the off leg must not even share a registry
            registry=telemetry.MetricsRegistry(),
            tracer=telemetry.TraceRecorder(),
            flight=telemetry.FlightRecorder() if introspect else None,
        )
        try:
            engine.warmup(params)
            engine.reset_stats()
            t0 = time.perf_counter()
            outs = engine.generate(params, prompts)
            wall_ms = (time.perf_counter() - t0) * 1e3
            results[introspect] = (outs, engine.stats(), wall_ms)
        finally:
            engine.close()
    assert results[False][0] == results[True][0], (
        "introspection changed produced tokens — parity violation"
    )
    off_ms, on_ms = results[False][2], results[True][2]
    for introspect in (False, True):
        print(json.dumps({
            "metric": "serve_introspection_wall_ms",
            "introspect": introspect,
            "requests": n_req,
            "new_tokens": new_tokens,
            "value": round(results[introspect][2], 1),
            "unit": "ms",
        }))
    programs = results[True][1]["programs"]
    decode = programs["engine.decode"]
    print(json.dumps({
        "metric": "serve_introspection_summary",
        "overhead_ms": round(on_ms - off_ms, 1),
        "overhead_pct": round(100.0 * (on_ms - off_ms) / max(off_ms, 1e-9), 2),
        "tokens_identical": True,
        "decode_calls": decode["calls"],
        "decode_compiles": decode["compiles"],
        "decode_flops_per_call": decode["flops_per_call"],
        "decode_bytes_per_call": decode["bytes_per_call"],
        "decode_mfu": decode["mfu"],
        "decode_hbm_utilization": decode["hbm_utilization"],
        "device": programs["device"],
        "unit": "ms",
    }))


def tracing_leg() -> None:
    """Distributed-tracing overhead report
    (``UNIONML_TPU_BENCH_PRESET=serve_tracing``).

    Runs the SAME request stream through a DecodeEngine twice — once
    bare, once with W3C trace-context propagation (every request
    submitted inside a ``trace_scope`` carrying a synthetic inbound
    ``traceparent``) AND a live OTLP exporter shipping every finished
    request's span tree plus metric snapshots to an in-process
    collector stub — asserts the produced tokens are bit-identical,
    and reports the per-request p50/p99 overhead delta. This is the
    number that keeps the "propagation + push export stay off the
    decode hot path" claim honest (the acceptance bar is ≤ 2% p99 on
    the CPU smoke configuration).
    """
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu import telemetry
    from unionml_tpu.exporters import OtlpCollectorStub, OtlpExporter
    from unionml_tpu.models import Llama, LlamaConfig
    from unionml_tpu.serving._stats import percentile_summary
    from unionml_tpu.serving.engine import DecodeEngine

    backend = jax.default_backend()
    if backend == "cpu":
        cfg = serving_config("tiny")
        module = Llama(cfg)
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
        n_req, clients, new_tokens, bucket, slots, chunk_steps = 48, 4, 8, 16, 4, 4
    else:
        cfg = serving_config("serve_1p5b")
        qcfg = LlamaConfig(**{**cfg.__dict__, "quantized": True})
        module = Llama(qcfg)
        params = random_quantized_params(module)
        n_req, clients, new_tokens, bucket, slots, chunk_steps = 128, 8, 32, 64, 8, 8
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, bucket // 2).tolist()
        for _ in range(n_req)
    ]
    results = {}
    for traced in (False, True):
        registry = telemetry.MetricsRegistry()
        tracer = telemetry.TraceRecorder(registry=registry)
        stub = exporter = None
        if traced:
            stub = OtlpCollectorStub()
            exporter = OtlpExporter(
                stub.endpoint, registry=registry, tracer=tracer,
                interval_s=0.25, seed=0,
            )
        engine = DecodeEngine(
            module, slots=slots, max_new_tokens=new_tokens,
            prompt_buckets=(bucket,), chunk_steps=chunk_steps,
            registry=registry, tracer=tracer,
            flight=telemetry.FlightRecorder(),
        )
        try:
            engine.warmup(params)
            engine.reset_stats()
            outs = [None] * n_req
            lat, lock = [], threading.Lock()

            def client(idx0):
                for i in range(idx0, n_req, clients):
                    ctx = telemetry.TraceContext(
                        telemetry.new_trace_id(), telemetry.new_span_id()
                    )
                    t0 = time.perf_counter()
                    if traced:
                        with telemetry.trace_scope(ctx):
                            out = engine.generate(params, [prompts[i]])
                    else:
                        out = engine.generate(params, [prompts[i]])
                    dt = (time.perf_counter() - t0) * 1e3
                    outs[i] = out[0]
                    with lock:
                        lat.append(dt)

            threads = [
                threading.Thread(target=client, args=(c,))
                for c in range(clients)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall_ms = (time.perf_counter() - t0) * 1e3
            exported = dropped = 0
            if exporter is not None:
                exporter.flush()
                exported = int(exporter._m_exported.value)
                dropped = int(exporter._m_dropped.value)
            results[traced] = {
                "outs": outs,
                "summary": percentile_summary(lat),
                "wall_ms": wall_ms,
                "exported_spans": exported,
                "dropped": dropped,
            }
        finally:
            engine.close()
            if exporter is not None:
                exporter.close(flush=False)
            if stub is not None:
                stub.close()
    assert results[False]["outs"] == results[True]["outs"], (
        "tracing + OTLP export changed produced tokens — parity violation"
    )
    for traced in (False, True):
        r = results[traced]
        print(json.dumps({
            "metric": "serve_tracing_latency_ms",
            "traced": traced,
            "requests": n_req,
            "clients": clients,
            "new_tokens": new_tokens,
            "p50_ms": r["summary"]["p50"],
            "value": r["summary"]["p99"],
            "wall_ms": round(r["wall_ms"], 1),
            "unit": "ms",
        }))
    off, on = results[False]["summary"], results[True]["summary"]
    print(json.dumps({
        "metric": "serve_tracing_summary",
        "tokens_identical": True,
        "p50_delta_pct": round(
            100.0 * (on["p50"] - off["p50"]) / max(off["p50"], 1e-9), 2
        ),
        "p99_delta_pct": round(
            100.0 * (on["p99"] - off["p99"]) / max(off["p99"], 1e-9), 2
        ),
        "exported_spans": results[True]["exported_spans"],
        "export_dropped": results[True]["dropped"],
        "unit": "pct",
    }))


def paged_leg() -> None:
    """Block-paged device KV at a fixed HBM byte budget
    (``UNIONML_TPU_BENCH_PRESET=serve_paged``).

    The workload paging exists for: a LONG-TAIL prompt mix (75% short
    prompts at 1/8 of the bucket, 25% at the full bucket) where the
    contiguous engine reserves every slot's worst case — bucket +
    max_new + pipeline spare — and the byte budget therefore caps the
    slot count. The paged engine spends the SAME budget on a global
    block pool; short prompts charge only their own blocks, so more
    sequences fit.

    Phase 1 — **effective batch at fixed budget**: the budget is what a
    ``contig_slots``-slot contiguous engine costs; both engines serve
    the same saturating stream while a sampler records peak concurrent
    residents. Acceptance: paged peak >= 1.5x contiguous peak, tokens
    bit-identical (the reference paged kernel).

    Phase 2 — **decode tokens/s at equal batch**: both engines at the
    SAME slot count, decode throughput recorded (paged must not
    regress when the layout is the only change); PR 4's per-program
    MFU/HBM gauges attribute where the time goes.
    """
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu import telemetry
    from unionml_tpu.models import Llama, LlamaConfig
    from unionml_tpu.serving.engine import DecodeEngine

    backend = jax.default_backend()
    if backend == "cpu":
        cfg = serving_config("tiny")
        # the parity assert is defined on the REFERENCE paged kernel
        # (bit-identical to the contiguous path by construction); the
        # Pallas kernel matches only up to float reduction order, so a
        # near-tie argmax could flip a greedy token and fail the bench
        # spuriously on TPU. Kernel speed is measured by the paged leg
        # of benchmarks/attn_kernels.py instead.
        module = Llama(
            LlamaConfig(**{**cfg.__dict__, "paged_impl": "reference"})
        )
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
        # new_tokens long enough that residents ACCUMULATE (the peak
        # must be memory-limited, not admission-rate-limited, for the
        # effective-batch comparison to measure the layout)
        n_req, new_tokens, bucket, chunk_steps = 24, 32, 64, 4
        blk, contig_slots, paged_slots = 16, 2, 8
    else:
        cfg = serving_config("serve_1p5b")
        qcfg = LlamaConfig(**{
            **cfg.__dict__, "quantized": True, "paged_impl": "reference",
        })
        module = Llama(qcfg)
        params = random_quantized_params(module)
        n_req, new_tokens, bucket, chunk_steps = 128, 32, 512, 8
        blk, contig_slots, paged_slots = 16, 4, 16
    rng = np.random.default_rng(0)
    prompts = []
    for i in range(n_req):
        # the long-tail mix: 75% short (bucket/8), 25% full-bucket
        n = bucket // 8 if i % 4 < 3 else bucket - 1
        prompts.append(rng.integers(1, cfg.vocab_size, n).tolist())

    def engine_for(paged: bool, slots: int, budget=None):
        kw = dict(
            slots=slots, max_new_tokens=new_tokens,
            prompt_buckets=(bucket,), chunk_steps=chunk_steps,
            registry=telemetry.MetricsRegistry(),
        )
        if paged:
            kw.update(paged=True, kv_block_size=blk)
            if budget is not None:
                kw.update(kv_pool_bytes=budget)
        return DecodeEngine(module, **kw)

    def run_stream(engine):
        """Serve the whole stream; sample peak concurrent residents."""
        peak = [0]
        stop = threading.Event()

        def sampler():
            while not stop.is_set():
                peak[0] = max(peak[0], int(engine._m_slots_busy.value))
                time.sleep(0.001)

        t = threading.Thread(target=sampler, daemon=True)
        engine.warmup(params)
        engine.reset_stats()
        t.start()
        t0 = time.perf_counter()
        outs = engine.generate(params, prompts)
        wall_s = time.perf_counter() - t0
        stop.set()
        t.join(timeout=5)
        stats = engine.stats()
        decode_tokens = sum(len(o) for o in outs)
        return {
            "outs": outs,
            "peak_batch": peak[0],
            "wall_s": wall_s,
            "tokens_per_s": decode_tokens / wall_s,
            "decode": stats.get("programs", {}).get("engine.decode", {}),
            "kv_pool": stats.get("kv_pool"),
        }

    # ---- phase 1: effective batch at a FIXED byte budget ----
    contig = engine_for(False, contig_slots)
    try:
        row_bytes = contig._kv_block_nbytes(1)
        budget = contig_slots * contig.cache_len * row_bytes
        r_contig = run_stream(contig)
    finally:
        contig.close()
    paged = engine_for(True, paged_slots, budget=budget)
    try:
        pool_blocks = paged.kv_pool.capacity
        r_paged = run_stream(paged)
    finally:
        paged.close()
    assert r_paged["outs"] == r_contig["outs"], (
        "paged KV changed produced tokens — parity violation"
    )
    assert r_paged["kv_pool"]["blocks_in_use"] == 0, (
        f"leaked pool blocks: {r_paged['kv_pool']}"
    )
    ratio = r_paged["peak_batch"] / max(1, r_contig["peak_batch"])
    for name, r in (("contiguous", r_contig), ("paged", r_paged)):
        print(json.dumps({
            "metric": "serve_paged_effective_batch",
            "layout": name,
            "budget_bytes": budget,
            "requests": n_req,
            "bucket": bucket,
            "new_tokens": new_tokens,
            "value": r["peak_batch"],
            "wall_s": round(r["wall_s"], 2),
            "decode_tokens_per_s": round(r["tokens_per_s"], 1),
            "decode_mfu": r["decode"].get("mfu"),
            "decode_hbm_utilization": r["decode"].get("hbm_utilization"),
            "unit": "concurrent residents",
        }))
    print(json.dumps({
        "metric": "serve_paged_summary",
        "effective_batch_ratio": round(ratio, 2),
        "block_size": blk,
        "pool_blocks": pool_blocks,
        "budget_bytes": budget,
        "tokens_identical": True,
        "pool_alloc_failures": r_paged["kv_pool"]["alloc_failures"],
        "unit": "x",
    }))
    assert ratio >= 1.5, (
        f"paged effective batch {r_paged['peak_batch']} < 1.5x contiguous "
        f"{r_contig['peak_batch']} at the same byte budget"
    )

    # ---- phase 2: decode tokens/s at EQUAL batch (layout-only delta) --
    equal = {}
    for is_paged in (False, True):
        e = engine_for(is_paged, contig_slots)
        try:
            equal[is_paged] = run_stream(e)
        finally:
            e.close()
    assert equal[True]["outs"] == equal[False]["outs"]
    for name, r in (("contiguous", equal[False]), ("paged", equal[True])):
        print(json.dumps({
            "metric": "serve_paged_equal_batch_tokens_per_s",
            "layout": name,
            "slots": contig_slots,
            "value": round(r["tokens_per_s"], 1),
            "wall_s": round(r["wall_s"], 2),
            "decode_mfu": r["decode"].get("mfu"),
            "decode_hbm_utilization": r["decode"].get("hbm_utilization"),
            "unit": "tokens/s",
        }))


def preempt_leg() -> None:
    """Preemptive, priority-aware scheduling under pool overload
    (``UNIONML_TPU_BENCH_PRESET=serve_preempt``; docs/robustness.md
    "Preemption & fairness").

    The workload preemption exists for: a low-priority BULK tenant
    floods the paged KV pool (more concurrent long decodes than the
    pool can hold resident) while a high-priority PREMIUM tenant keeps
    sending short interactive requests. Without the scheduler the
    premium requests queue FIFO behind the bulk backlog and a full
    pool; with it they jump the parked bulk head (promote), evict a
    bulk resident to the host prefix-cache store when blocks are short
    (preempt), and the victims resume via the splice path.

    Phase 1 — **unloaded baseline**: the premium stream alone on the
    warmed engine; per-request wall-time p99 recorded (min over
    rounds — CPU scheduler tails).

    Phase 2 — **overload**: the bulk flood saturates the pool, then
    the same premium stream runs high-priority through the contention.

    Acceptance: premium p99 under overload holds within **1.5x** of
    its unloaded baseline, at least one preemption actually fired,
    every preempted bulk stream reaches exact token parity with its
    solo run, and there are ZERO caller-visible failures.
    """
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu import telemetry
    from unionml_tpu.models import Llama, LlamaConfig
    from unionml_tpu.models.generate import make_generator
    from unionml_tpu.serving.engine import DecodeEngine
    from unionml_tpu.serving.prefix_cache import RadixPrefixCache

    backend = jax.default_backend()
    if backend == "cpu":
        cfg = serving_config("tiny")
        module = Llama(
            LlamaConfig(**{**cfg.__dict__, "paged_impl": "reference"})
        )
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
        bulk_clients, bulk_per_client, premium_n = 4, 3, 12
        bulk_len, bulk_new, prem_len, prem_new = 16, 48, 8, 8
        bucket, blk, slots, rounds = 64, 16, 4, 3
        # capacity fits TWO bulk residents (ceil((16+48)/16)=4 blocks
        # each): the 4-client flood keeps the pool exhausted, and the
        # long bulk decodes make waiting for a natural retirement
        # strictly worse than preempting
        pool_blocks = 9
    else:
        cfg = serving_config("serve_1p5b")
        qcfg = LlamaConfig(**{
            **cfg.__dict__, "quantized": True, "paged_impl": "reference",
        })
        module = Llama(qcfg)
        params = random_quantized_params(module)
        bulk_clients, bulk_per_client, premium_n = 8, 4, 32
        bulk_len, bulk_new, prem_len, prem_new = 128, 128, 32, 16
        bucket, blk, slots, rounds = 512, 16, 8, 3
        pool_blocks = 1 + 4 * ((bulk_len + bulk_new) // blk)

    registry = telemetry.MetricsRegistry()
    engine = DecodeEngine(
        module, slots=slots, max_new_tokens=max(bulk_new, prem_new),
        prompt_buckets=(bucket,), chunk_steps=4, paged=True,
        # a shallow pipeline bounds the deferred-free fence an evicted
        # victim's blocks wait behind — the dominant term in the
        # premium tenant's preempt-then-admit latency
        pipeline_depth=2,
        kv_block_size=blk, kv_pool_blocks=pool_blocks,
        prefix_cache=RadixPrefixCache(block_size=blk, registry=registry),
        registry=registry,
    )
    rng = np.random.default_rng(0)
    bulk_prompts = [
        rng.integers(1, cfg.vocab_size, bulk_len).tolist()
        for _ in range(bulk_clients * bulk_per_client)
    ]
    prem_prompts = [
        rng.integers(1, cfg.vocab_size, prem_len).tolist()
        for _ in range(premium_n)
    ]
    solo_bulk = make_generator(
        module, max_new_tokens=bulk_new, max_len=engine.cache_len
    )
    solo_prem = make_generator(
        module, max_new_tokens=prem_new, max_len=engine.cache_len
    )

    def solo(gen, prompt):
        return np.asarray(
            gen(params, jnp.asarray([prompt], jnp.int32))
        )[0].tolist()

    # ONE solo reference per distinct prompt (the premium stream
    # re-runs rounds x 2 times — recomputing its references each pass
    # would multiply the oracle's device work for identical answers)
    prem_solo = {tuple(p): solo(solo_prem, p) for p in prem_prompts}

    def premium_pass():
        """Sequential premium stream; per-request DECODE latency
        (first harvested chunk → stream end, measured client-side via
        the SSE-shaped generator — the ISSUE's bar: queue/admission
        wait under overload is what the promote/preempt machinery
        spends, decode-lane progress is what it protects)."""
        decode_ms = []
        for p in prem_prompts:
            out: list = []
            t_first = None
            for chunk in engine.generate_stream(
                params, p, max_new_tokens=prem_new,
                tenant="premium", priority="high",
            ):
                if t_first is None:
                    t_first = time.perf_counter()
                out.extend(chunk)
            decode_ms.append((time.perf_counter() - t_first) * 1e3)
            assert out == prem_solo[tuple(p)], "premium token parity"
        return decode_ms

    def premium_phase():
        """Per-request MIN over rounds, then nearest-rank p99 across
        requests (the PR 8 estimator lessons: a nearest-rank p99 of a
        dozen samples IS the max, so one CPU-scheduler tail decides
        the stat — the per-request min cancels it while keeping the
        loaded-vs-unloaded contrast the bar is about)."""
        per_req = None
        for _ in range(rounds):
            ms = premium_pass()
            per_req = (
                ms if per_req is None
                else [min(a, b) for a, b in zip(per_req, ms)]
            )
        per_req.sort()
        return per_req[max(0, math.ceil(0.99 * len(per_req)) - 1)]

    try:
        engine.warmup(params)
        engine.prefix_cache.clear()

        # ---- phase 1: unloaded premium baseline ----
        p99_base = premium_phase()

        # ---- phase 2: bulk flood + premium through the contention --
        failures: list = []
        bulk_outs: dict = {}
        lock = threading.Lock()

        def bulk_client(idx: int):
            for j in range(bulk_per_client):
                p = bulk_prompts[idx * bulk_per_client + j]
                try:
                    out = engine.generate(
                        params, [p], max_new_tokens=bulk_new,
                        tenant="bulk", priority="low",
                    )[0]
                    with lock:
                        bulk_outs[tuple(p)] = out
                except Exception as exc:  # ZERO of these allowed
                    with lock:
                        failures.append(repr(exc))

        threads = [
            threading.Thread(target=bulk_client, args=(i,), daemon=True)
            for i in range(bulk_clients)
        ]
        for t in threads:
            t.start()
        # wait for real pool pressure before measuring the premium leg
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if engine.stats()["kv_pool"]["alloc_failures"] > 0:
                break
            time.sleep(0.002)
        p99_loaded = premium_phase()
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), "bulk stream hung"
        assert not failures, f"caller-visible failures: {failures}"
        # preempted bulk streams reached exact token parity
        for p in bulk_prompts:
            assert bulk_outs[tuple(p)] == solo(solo_bulk, p), (
                "preempted bulk stream lost token parity"
            )
        stats = engine.stats()
        preemptions = stats["scheduler"]["preemptions"]
        pool = stats["kv_pool"]
        ratio = p99_loaded / max(1e-9, p99_base)
        print(json.dumps({
            "metric": "serve_preempt_premium_decode_p99_ms",
            "unloaded": round(p99_base, 2),
            "overloaded": round(p99_loaded, 2),
            "ratio": round(ratio, 3),
            "bound": 1.5,
            "unit": "ms",
        }))
        print(json.dumps({
            "metric": "serve_preempt_summary",
            "preemptions": preemptions,
            "preempted_blocks": pool["preempted_blocks"],
            "alloc_failures": pool["alloc_failures"],
            "bulk_requests": len(bulk_prompts),
            "premium_requests": premium_n * rounds * 2,
            "caller_visible_failures": 0,
            "tokens_identical": True,
            "unit": "",
        }))
        assert preemptions >= 1, (
            "the overload never triggered a preemption — the scenario "
            "is not exercising the scheduler"
        )
        assert pool["blocks_in_use"] == 0, f"leaked pool blocks: {pool}"
        assert ratio <= 1.5, (
            f"premium p99 decode latency {p99_loaded:.1f} ms under "
            f"overload exceeds 1.5x its unloaded baseline "
            f"{p99_base:.1f} ms"
        )
    finally:
        engine.close()


def usage_leg() -> None:
    """Per-tenant usage metering: attribution identity, cardinality
    bound, and ledger overhead
    (``UNIONML_TPU_BENCH_PRESET=serve_usage``).

    Phase 1 — **attribution identity + cardinality**: a mixed 3-tenant
    stream (interleaved concurrent clients, uneven request counts)
    through a ledger-on engine. Asserts per-tenant attributed
    device-seconds and tokens each explain >= 95% of the engine totals
    (the measurement-substrate contract fair scheduling will build on),
    then fires a burst of 40 distinct one-request tenants and asserts
    the exported ``unionml_tenant_*`` label cardinality stays
    <= top_k + 1 (the ``other`` rollup absorbing the tail).

    Phase 2 — **overhead at token parity**: the same prompts through
    ONE engine with the ledger toggled on/off between rounds (the
    ``engine.usage`` idle-swap seam), tokens asserted bit-identical,
    per-request p99 delta asserted <= 2%. The estimator is built for a
    2% bar on a millisecond-scale CPU workload (the goodput bench's
    overhead-leg lessons, adapted):

    - BOTH legs run on the SAME engine instance — two separately-
      constructed engines differ by several percent (p50 included)
      from thread/allocator placement alone, a persistent instance
      bias that min-over-rounds cannot wash out; toggling the seam
      leaves only the ledger's own cost in the delta,
    - the stream is SEQUENTIAL — per-request p99 under 4 GIL-bound
      client threads differs +-5% between two IDENTICAL ledger-off
      engines (scheduler tails), swamping the bar; concurrency belongs
      to phase 1's attribution identity, the overhead question is
      per-request cost,
    - legs are paired PER REQUEST (each request runs ledger-off and
      ledger-on back-to-back, order alternating by round+index), not
      per pass — the host's minute-scale drift moves whole sequential
      passes by +-2%, which leg-level alternation leaves on one leg
      but a milliseconds-apart pair cancels,
    - per-request MIN over rounds, then nearest-rank p99 across
      requests, UNROUNDED (``percentile_summary`` rounds to 0.1 ms =
      2% of this workload): the min discards scheduler outliers per
      request the way interleaved min-of-N discards bad rounds, while
      the p99 across requests keeps the workload's own tail,
    - gc paused over the timed rounds (a collection mid-round lands a
      ~30 ms outlier on whichever leg happens to be running).
    """
    import gc
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu import telemetry
    from unionml_tpu.models import Llama, LlamaConfig
    from unionml_tpu.serving.engine import DecodeEngine
    from unionml_tpu.serving.usage import UsageLedger, tenant_scope

    backend = jax.default_backend()
    if backend == "cpu":
        cfg = serving_config("tiny")
        module = Llama(cfg)
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
        n_req, new_tokens, bucket, slots, chunk_steps = 48, 8, 16, 4, 4
        rounds = 6
    else:
        cfg = serving_config("serve_1p5b")
        qcfg = LlamaConfig(**{**cfg.__dict__, "quantized": True})
        module = Llama(qcfg)
        params = random_quantized_params(module)
        n_req, new_tokens, bucket, slots, chunk_steps = 128, 32, 64, 8, 8
        rounds = 4
    top_k = 4
    burst_tenants = 40
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, bucket // 2).tolist()
        for _ in range(n_req)
    ]
    # uneven tenant mix: tenant-a 3/6, tenant-b 2/6, tenant-c 1/6
    mix = ("tenant-a", "tenant-a", "tenant-a", "tenant-b", "tenant-b",
           "tenant-c")
    tenants = [mix[i % len(mix)] for i in range(n_req)]

    def run_stream(engine, traced_tenants):
        """Serve the stream with `clients` concurrent workers, each
        request under its tenant's scope; outputs index-aligned."""
        clients = 4
        outs = [None] * n_req

        def client(idx0):
            for i in range(idx0, n_req, clients):
                with tenant_scope(traced_tenants[i]):
                    out = engine.generate(params, [prompts[i]])
                outs[i] = out[0]

        threads = [
            threading.Thread(target=client, args=(c,))
            for c in range(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return outs

    # ---- phase 1: attribution identity + cardinality bound ----
    registry = telemetry.MetricsRegistry()
    ledger = UsageLedger(registry=registry, top_k=top_k)
    engine = DecodeEngine(
        module, slots=slots, max_new_tokens=new_tokens,
        prompt_buckets=(bucket,), chunk_steps=chunk_steps,
        registry=registry, tracer=telemetry.TraceRecorder(),
        flight=telemetry.FlightRecorder(), usage=ledger,
    )
    try:
        engine.warmup(params)
        engine.reset_stats()
        run_stream(engine, tenants)
        report = ledger.report()
        per_tenant = report["tenants"]
        attributed_s = report["attribution"]["attributed_device_seconds"]
        attributed_tok = report["attribution"]["attributed_tokens"]
        totals = report["totals"]
        s_cov = report["attribution"]["device_seconds_coverage"]
        t_cov = report["attribution"]["token_coverage"]
        print(json.dumps({
            "metric": "serve_usage_attribution",
            "requests": n_req,
            "tenants": {
                t: {
                    "device_seconds": v["device_seconds"],
                    "decode_tokens": v["decode_tokens"],
                    "requests": v["requests"],
                }
                for t, v in per_tenant.items()
            },
            "total_device_seconds": totals["device_seconds"],
            "total_tokens": totals["tokens"],
            "attributed_device_seconds": attributed_s,
            "attributed_tokens": attributed_tok,
            "value": s_cov,
            "token_coverage": t_cov,
            "capacity_headroom": report["capacity"]["headroom"],
            "unit": "coverage ratio",
        }))
        assert s_cov >= 0.95, (
            f"attributed device-seconds cover only {s_cov:.3f} of "
            "engine totals (bar: 0.95)"
        )
        assert t_cov >= 0.95, (
            f"attributed tokens cover only {t_cov:.3f} of engine "
            "totals (bar: 0.95)"
        )
        # cardinality: a burst of distinct one-request tenants must
        # roll into `other`, not mint series
        for i in range(burst_tenants):
            with tenant_scope(f"burst-{i}"):
                engine.generate(params, [prompts[i % n_req]])
        text = registry.exposition()
        label_values = set()
        for line in text.splitlines():
            if line.startswith("unionml_tenant_") and 'tenant="' in line:
                label_values.add(
                    line.split('tenant="', 1)[1].split('"', 1)[0]
                )
        print(json.dumps({
            "metric": "serve_usage_cardinality",
            "distinct_tenants": ledger.report()["distinct_tenants"],
            "top_k": top_k,
            "exported_tenant_labels": sorted(label_values),
            "value": len(label_values),
            "unit": "label values",
        }))
        assert len(label_values) <= top_k + 1, (
            f"exported tenant-label cardinality {len(label_values)} "
            f"exceeds top_k + 1 = {top_k + 1}: {sorted(label_values)}"
        )
    finally:
        engine.close()

    # ---- phase 2: overhead at token parity (sequential paired rounds,
    # alternating leg order, per-request min, unrounded p99) ----
    # per-request base doubled on CPU so the ledger's ~10 us/chunk and
    # the timer/scheduler jitter are small FRACTIONS of every sample
    p2_new_tokens = new_tokens * 2 if backend == "cpu" else new_tokens
    # sample sizes sized for the nearest-rank p99 of per-request MINs:
    # at n=48 that rank IS the maximum, so one request unlucky in every
    # round decides the stat — >=120 requests drop the single worst,
    # and 10 rounds tighten each request's min (an outlier must recur
    # in ALL rounds to survive)
    p2_n_req, p2_rounds = (120, 10) if backend == "cpu" else (128, rounds)
    p2_prompts = [
        rng.integers(1, cfg.vocab_size, bucket // 2).tolist()
        for _ in range(p2_n_req)
    ]
    p2_tenants = [mix[i % len(mix)] for i in range(p2_n_req)]
    # ONE engine for both legs, toggling the off-switch seam between
    # rounds (swapped only while idle): two separately-constructed
    # engines differ by several percent — p50 included — from thread/
    # allocator placement alone on this host, a persistent instance
    # bias that per-request min-over-rounds cannot wash out because
    # every round of the slow leg runs on the slow instance. The
    # attribution window is clamped at dispatch time, so the off-leg's
    # idle gap never inflates the first on-leg window.
    registry = telemetry.MetricsRegistry()
    p2_engine = DecodeEngine(
        module, slots=slots, max_new_tokens=p2_new_tokens,
        prompt_buckets=(bucket,), chunk_steps=chunk_steps,
        registry=registry, tracer=telemetry.TraceRecorder(),
        flight=telemetry.FlightRecorder(), usage=None,
    )
    p2_ledger = UsageLedger(registry=registry)

    try:
        p2_engine.warmup(params)
        p2_engine.reset_stats()
        per_req = {m: [[] for _ in range(p2_n_req)] for m in (False, True)}
        outs = {m: [None] * p2_n_req for m in (False, True)}
        gc.collect()
        gc.disable()
        try:
            for r in range(p2_rounds):
                for i in range(p2_n_req):
                    # request-level pairing: each request runs BOTH
                    # legs back-to-back (~ms apart, order alternating
                    # by round+index), so the host's minute-scale
                    # drift — which moved whole leg-level passes by
                    # +-2% and swamped the bar — cancels within the
                    # pair instead of landing on one leg
                    legs = (
                        (False, True) if (r + i) % 2 == 0
                        else (True, False)
                    )
                    for metered in legs:
                        p2_engine.usage = p2_ledger if metered else None
                        t0 = time.perf_counter()
                        with tenant_scope(p2_tenants[i]):
                            out = p2_engine.generate(
                                params, [p2_prompts[i]]
                            )
                        per_req[metered][i].append(
                            (time.perf_counter() - t0) * 1e3
                        )
                        outs[metered][i] = out[0]
        finally:
            p2_engine.usage = None
            gc.enable()
        assert outs[False] == outs[True], (
            "usage metering changed produced tokens — parity violation"
        )

        def tail_p99(metered: bool) -> float:
            best = sorted(min(vs) for vs in per_req[metered])
            return best[max(0, math.ceil(0.99 * len(best)) - 1)]

        off_p99, on_p99 = tail_p99(False), tail_p99(True)
        overhead_pct = 100.0 * (on_p99 - off_p99) / max(off_p99, 1e-9)
        for metered in (False, True):
            best = [min(vs) for vs in per_req[metered]]
            print(json.dumps({
                "metric": "serve_usage_latency_p99_ms",
                "metered": metered,
                "requests": p2_n_req,
                "rounds": p2_rounds,
                "new_tokens": p2_new_tokens,
                "protocol": "sequential, per-request paired legs, "
                            "min-per-request over rounds",
                "value": round(tail_p99(metered), 3),
                "p50_ms": round(sorted(best)[len(best) // 2], 3),
                "unit": "ms",
            }))
        print(json.dumps({
            "metric": "serve_usage_summary",
            "tokens_identical": True,
            "value": round(overhead_pct, 2),
            "unit": "pct p99 overhead",
        }))
        assert overhead_pct <= 2.0, (
            f"usage-ledger p99 overhead {overhead_pct:.2f}% exceeds "
            "the 2% bar"
        )
    finally:
        p2_engine.close()


def overload_leg() -> None:
    """Admission control + supervised recovery under saturation
    (``UNIONML_TPU_BENCH_PRESET=serve_overload``).

    Phase 1 — **over-admitted stream**: more concurrent clients than
    the bounded engine (slots + ``max_queue_depth``) can hold, no
    client backoff. Reports the shed rate (Overloaded rejections /
    offered requests) and the accepted requests' p50/p99 — the
    admission-control contract: bounded latency for what is accepted,
    fast typed rejection for the rest, instead of unbounded queueing
    where EVERY request eventually times out.

    Phase 2 — **recovery time**: with every slot resident, a
    FaultInjector raises an OOM-shaped XLA error on the next decode
    dispatch; the metric is the wall time from arming the fault to the
    first successfully completed request on the rebuilt state.
    """
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.models import Llama, LlamaConfig
    from unionml_tpu.serving._stats import percentile_summary
    from unionml_tpu.serving.engine import DecodeEngine
    from unionml_tpu.serving.faults import (
        FaultInjector, Overloaded, xla_oom_error,
    )

    backend = jax.default_backend()
    if backend == "cpu":
        cfg = serving_config("tiny")
        module = Llama(cfg)
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
        n_req, clients, slots, queue_depth = 48, 8, 2, 4
        new_tokens, bucket, chunk_steps = 16, 16, 4
    else:
        cfg = serving_config("serve_1p5b")
        qcfg = LlamaConfig(**{**cfg.__dict__, "quantized": True})
        module = Llama(qcfg)
        params = random_quantized_params(module)
        n_req, clients, slots, queue_depth = 256, 32, 8, 16
        new_tokens, bucket, chunk_steps = 32, 64, 8
    fi = FaultInjector()
    engine = DecodeEngine(
        module, slots=slots, max_new_tokens=new_tokens,
        prompt_buckets=(bucket,), chunk_steps=chunk_steps,
        max_queue_depth=queue_depth, fault_injector=fi,
    )
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(1, cfg.vocab_size, bucket // 2).tolist()
        for _ in range(n_req)
    ]
    try:
        engine.warmup(params)
        engine.reset_stats()

        lat, shed, failed, lock = [], [0], [], threading.Lock()

        def client(rows):
            for p in rows:
                t0 = time.perf_counter()
                try:
                    engine.generate(params, [p])
                except Overloaded:
                    with lock:
                        shed[0] += 1
                    continue
                except Exception as exc:
                    # anything else (timeout, breaker, ...) must be
                    # COUNTED, not silently truncate the sample — a
                    # survivorship-biased p99 would report a healthy
                    # tail exactly when the system is misbehaving
                    with lock:
                        failed.append(f"{type(exc).__name__}: {exc}")
                    continue
                with lock:
                    lat.append((time.perf_counter() - t0) * 1e3)

        threads = [
            threading.Thread(target=client, args=(prompts[i::clients],))
            for i in range(clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall_ms = (time.perf_counter() - t0) * 1e3
        s = percentile_summary(lat)
        print(json.dumps({
            "metric": "serve_overload_accepted_p99_ms",
            "offered": n_req,
            "clients": clients,
            "slots": slots,
            "max_queue_depth": queue_depth,
            "accepted": len(lat),
            "shed": shed[0],
            "failed": len(failed),
            "failed_errors": sorted(set(failed))[:3],
            "shed_rate": round(shed[0] / n_req, 3),
            "value": round(s.get("p99", 0.0), 1),
            "p50_ms": round(s.get("p50", 0.0), 1),
            "wall_ms": round(wall_ms, 1),
            "unit": "ms",
        }))

        # ---- phase 2: recovery time after an injected device fault ----
        def occupant(p):
            try:
                engine.generate(params, [p])
            except BaseException:
                pass  # the poisoned batch: expected to fail

        occ = [
            threading.Thread(target=occupant, args=(prompts[i],))
            for i in range(slots)
        ]
        for t in occ:
            t.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            with engine._lock:  # resident-count poll (bench-only peek)
                if sum(r is not None for r in engine._occupant) == slots:
                    break
            time.sleep(0.002)
        fi.arm("engine.dispatch", exc=xla_oom_error())
        t_fault = time.perf_counter()
        while True:  # first completed request marks recovered service
            try:
                engine.generate(params, [prompts[0]])
                break
            except Exception:
                time.sleep(0.002)
        recovery_ms = (time.perf_counter() - t_fault) * 1e3
        for t in occ:
            t.join(timeout=60)
        print(json.dumps({
            "metric": "serve_overload_recovery_ms",
            "slots": slots,
            "value": round(recovery_ms, 1),
            "recoveries": engine.stats()["robustness"]["recoveries"],
            "unit": "ms",
        }))
    finally:
        engine.close()


def router_leg() -> None:
    """Fleet-router robustness + overhead
    (``UNIONML_TPU_BENCH_PRESET=serve_router``).

    Phase 1 — **chaos under traffic**: 3 engine replicas behind a
    ``FleetRouter``, concurrent clients streaming requests. Mid-run,
    one replica takes an OOM-shaped device fault on a decode dispatch
    (the poisoned batch dies inside that engine; the router's retry
    envelope absorbs it) and another replica is drained and rejoined
    (the rolling-restart choreography). Asserts: ZERO caller-visible
    failures, every response token-identical to its solo run, and
    total retries within the fleet retry budget
    (``burst + ratio * requests`` — the storm-control bound,
    docs/robustness.md "Fleet robustness").

    Phase 2 — **passthrough overhead**: the same engine serves the
    same requests directly and through a 1-replica router,
    interleaved per request in alternating order (the PR 8 estimator
    lessons: whole-pass legs drift percents at minute scale; pairing
    per request cancels it), per-request MIN over rounds, nearest-rank
    p99 computed UNROUNDED. Asserts the router adds <= 2% p99 and
    bit-identical tokens.
    """
    import gc
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu import telemetry
    from unionml_tpu.models import Llama
    from unionml_tpu.serving.engine import DecodeEngine
    from unionml_tpu.serving.faults import FaultInjector, xla_oom_error
    from unionml_tpu.serving.router import (
        EngineReplica, FleetRouter, RouterPolicy,
    )

    backend = jax.default_backend()
    if backend == "cpu":
        cfg = serving_config("tiny")
        module = Llama(cfg)
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
        n_req, clients, slots = 48, 6, 2
        new_tokens, bucket, chunk_steps = 16, 16, 4
        overhead_reqs, overhead_rounds = 40, 6
    else:
        cfg = serving_config("serve_1p5b")
        module = Llama(cfg)
        params = random_quantized_params(module)
        n_req, clients, slots = 192, 24, 8
        new_tokens, bucket, chunk_steps = 32, 64, 8
        overhead_reqs, overhead_rounds = 120, 8

    n_replicas = 3
    ratio, burst = 0.2, 3.0
    fis = [FaultInjector() for _ in range(n_replicas)]
    engines = [
        DecodeEngine(
            module, slots=slots, max_new_tokens=new_tokens,
            prompt_buckets=(bucket,), chunk_steps=chunk_steps,
            max_queue_depth=64, fault_injector=fis[i],
        )
        for i in range(n_replicas)
    ]
    registry = telemetry.MetricsRegistry()
    flight = telemetry.FlightRecorder()
    router = FleetRouter(
        [
            EngineReplica(engines[i], params, name=f"r{i}")
            for i in range(n_replicas)
        ],
        policy=RouterPolicy(
            retry_budget_ratio=ratio, retry_budget_burst=burst,
            backoff_base_s=0.001, jitter_s=0.0, health_ttl_s=0.05,
        ),
        registry=registry,
        flight=flight,
    )
    rng = np.random.default_rng(0)
    # a small distinct-prompt set reused across the stream keeps the
    # solo-parity oracle cheap (one solo run per distinct prompt)
    distinct = [
        rng.integers(1, cfg.vocab_size, bucket // 2).tolist()
        for _ in range(8)
    ]
    try:
        for e in engines:
            e.warmup(params)
        solo = {
            tuple(p): engines[0].generate(params, [p])[0] for p in distinct
        }
        for e in engines:
            e.reset_stats()

        results, failures, lock = [], [], threading.Lock()
        started = threading.Event()

        def client(idx):
            for j, p in enumerate(
                distinct[(idx + k) % len(distinct)]
                for k in range(n_req // clients)
            ):
                if idx == 0 and j == 1:
                    started.set()  # traffic confirmed in flight
                try:
                    out = router.generate(p)
                    with lock:
                        results.append((tuple(p), out))
                except BaseException as exc:  # EVERY failure counts
                    with lock:
                        failures.append(f"{type(exc).__name__}: {exc}")

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(clients)
        ]
        for t in threads:
            t.start()
        started.wait(timeout=60)
        # mid-run: KILL r0 (next decode dispatch dies OOM-shaped) ...
        fis[0].arm("engine.dispatch", exc=xla_oom_error())
        time.sleep(0.05)
        # ... and roll r2: drain (in-flight streams finish), rejoin
        router.drain_replica("r2", timeout=120)
        time.sleep(0.02)
        router.rejoin_replica("r2")
        for t in threads:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in threads), "clients hung"

        assert not failures, (
            f"{len(failures)} caller-visible failures (want 0): "
            f"{sorted(set(failures))[:3]}"
        )
        bad = sum(1 for key, out in results if out != solo[key])
        assert bad == 0, f"{bad}/{len(results)} responses lost token parity"
        assert fis[0].injected("engine.dispatch") == 1, (
            "the replica kill must actually have fired"
        )
        retries = sum(
            child.value
            for _, child in router._m_retries.children()
        )
        budget_cap = burst + ratio * n_req
        assert retries <= budget_cap, (
            f"retry amplification {retries} exceeds budget {budget_cap}"
        )
        kinds = {e["kind"] for e in flight.dump()}
        assert {"route", "retry", "drain", "rejoin"} <= kinds, kinds
        print(json.dumps({
            "metric": "serve_router_failover",
            "replicas": n_replicas,
            "offered": n_req,
            "clients": clients,
            "completed": len(results),
            "caller_visible_failures": len(failures),
            "retries": retries,
            "retry_budget_cap": budget_cap,
            "recoveries_r0": engines[0].stats()["robustness"]["recoveries"],
            "drain_rejoin_cycles": 1,
            "token_parity": "exact",
            "unit": "requests",
        }))
    finally:
        for e in engines:
            e.close()

    # ---- phase 2: 1-replica passthrough overhead vs direct engine ----
    engine = DecodeEngine(
        module, slots=slots, max_new_tokens=new_tokens,
        prompt_buckets=(bucket,), chunk_steps=chunk_steps,
    )
    router1 = FleetRouter(
        [EngineReplica(engine, params, name="solo")],
        policy=RouterPolicy(health_ttl_s=0.05),
        registry=telemetry.MetricsRegistry(),
        flight=telemetry.FlightRecorder(),
    )
    try:
        engine.warmup(params)
        prompts = [
            rng.integers(1, cfg.vocab_size, bucket // 2).tolist()
            for _ in range(overhead_reqs)
        ]
        direct_min = [math.inf] * overhead_reqs
        routed_min = [math.inf] * overhead_reqs
        token_mismatch = 0
        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            for r in range(overhead_rounds):
                for i, p in enumerate(prompts):
                    legs = [("direct", i), ("routed", i)]
                    if (r + i) % 2:
                        legs.reverse()  # drift cancels inside the pair
                    outs = {}
                    for legname, idx in legs:
                        t0 = time.perf_counter()
                        if legname == "direct":
                            out = engine.generate(params, [p])[0]
                            dt = time.perf_counter() - t0
                            direct_min[idx] = min(direct_min[idx], dt)
                        else:
                            out = router1.generate(p)
                            dt = time.perf_counter() - t0
                            routed_min[idx] = min(routed_min[idx], dt)
                        outs[legname] = out
                    if outs["direct"] != outs["routed"]:
                        token_mismatch += 1
        finally:
            if gc_was_enabled:
                gc.enable()
        assert token_mismatch == 0, (
            f"{token_mismatch} routed responses diverged from direct"
        )

        def p99(vals):  # nearest-rank, UNROUNDED (0.1 ms rounding is
            v = sorted(vals)  # percents of this workload)
            return v[max(0, math.ceil(0.99 * len(v)) - 1)]

        d99, r99 = p99(direct_min), p99(routed_min)
        overhead = (r99 - d99) / d99 if d99 > 0 else 0.0
        assert overhead <= 0.02, (
            f"router passthrough adds {overhead:.1%} p99 "
            f"(direct {d99 * 1e3:.2f} ms vs routed {r99 * 1e3:.2f} ms); "
            "bar is 2%"
        )
        print(json.dumps({
            "metric": "serve_router_passthrough_p99_overhead",
            "requests": overhead_reqs,
            "rounds": overhead_rounds,
            "direct_p99_ms": round(d99 * 1e3, 3),
            "routed_p99_ms": round(r99 * 1e3, 3),
            "value": round(overhead * 100, 2),
            "token_parity": "exact",
            "unit": "percent",
        }))
        print(json.dumps({
            "metric": "serve_router_summary",
            "failover": "0 caller-visible failures, parity exact",
            "retry_budget": "bounded",
            "passthrough_p99_overhead_pct": round(overhead * 100, 2),
        }))
    finally:
        engine.close()


def autoscale_leg() -> None:
    """Self-operating fleet
    (``UNIONML_TPU_BENCH_PRESET=serve_autoscale``).

    One continuous chaos scenario on a 2-replica baseline fleet with a
    closed-loop :class:`~unionml_tpu.serving.autoscaler
    .FleetAutoscaler` (docs/robustness.md "Autoscaling &
    self-healing"):

    1. **Burn-induced scale-out, fleet-warmed.** A concurrent
       shared-prefix flood drives the fleet TTFT objective into
       sustained fast+slow-window burn; the autoscaler provisions a
       third replica WITHIN the SLO fast window, warm-joined from the
       warmest donor's hot prefix blocks — the joiner's first
       shared-prefix request is asserted to HIT (prefill tokens
       saved > 0 against imported-only content).
    2. **Mid-run kill, replaced automatically.** A replica takes an
       OOM-shaped device fault and then reads as a dead process; the
       router absorbs the in-flight failures (retries), the
       autoscaler reaps the corpse and provisions its replacement.
    3. **Load drop, scale-in.** The flood ends, burn clears, and the
       fleet consolidates back to the 2-replica baseline through the
       hysteresis band.

    Asserts ZERO caller-visible failures and exact per-request token
    parity vs the solo oracle across all three phases, and that every
    scale decision is present in the flight record.
    """
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu import telemetry
    from unionml_tpu.models import Llama
    from unionml_tpu.serving.autoscaler import (
        AutoscalerPolicy, EngineReplicaProvisioner, FleetAutoscaler,
    )
    from unionml_tpu.serving.engine import DecodeEngine
    from unionml_tpu.serving.faults import (
        EngineUnavailable, FaultInjector, xla_oom_error,
    )
    from unionml_tpu.serving.router import (
        EngineReplica, FleetRouter, RouterPolicy,
    )
    from unionml_tpu.serving.usage import UsageLedger
    from unionml_tpu.slo import LatencyObjective, SloWatchdog

    backend = jax.default_backend()
    if backend == "cpu":
        cfg = serving_config("tiny")
        module = Llama(cfg)
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
        n_req, clients, slots = 2400, 8, 2
        new_tokens, bucket, chunk_steps = 16, 32, 4
        ttft_threshold_ms = 10.0
    else:
        cfg = serving_config("serve_1p5b")
        module = Llama(cfg)
        params = random_quantized_params(module)
        n_req, clients, slots = 384, 32, 4
        new_tokens, bucket, chunk_steps = 32, 64, 8
        ttft_threshold_ms = 250.0

    registry = telemetry.MetricsRegistry()
    flight = telemetry.FlightRecorder()
    ledger = UsageLedger(registry=registry)
    fi0 = FaultInjector()

    def make_engine(fi=None):
        return DecodeEngine(
            module, slots=slots, max_new_tokens=new_tokens,
            prompt_buckets=(bucket,), chunk_steps=chunk_steps,
            prefix_cache=True, usage=ledger, max_queue_depth=128,
            registry=registry,
            **({"fault_injector": fi} if fi is not None else {}),
        )

    class KillableEngineReplica(EngineReplica):
        """Models a crashed process: the armed fault poisons the
        in-flight batch (retryable), the kill flag makes every later
        dispatch/health read unreachable."""

        killed = False

        def kill(self):
            self.killed = True

        def generate_stream(self, prompt, *, max_new_tokens=None):
            if self.killed:
                raise EngineUnavailable(
                    f"{self.name} process died", reason="unreachable",
                )
            return super().generate_stream(
                prompt, max_new_tokens=max_new_tokens
            )

        def generate(self, prompt, *, max_new_tokens=None):
            if self.killed:
                raise EngineUnavailable(
                    f"{self.name} process died", reason="unreachable",
                )
            return super().generate(prompt, max_new_tokens=max_new_tokens)

        def health(self):
            if self.killed:
                raise ConnectionError(f"{self.name} process died")
            return super().health()

    engines = [make_engine(fi0), make_engine()]
    replicas = [
        KillableEngineReplica(engines[i], params, name=f"r{i}")
        for i in range(2)
    ]
    router = FleetRouter(
        replicas,
        policy=RouterPolicy(
            health_ttl_s=0.0, jitter_s=0.0, backoff_base_s=0.001,
            max_attempts=4, retry_budget_burst=50.0,
            retry_budget_ratio=1.0, eject_consecutive=1,
            eject_cooldown_s=1000.0,   # corpses stay ejected; reap ends them
        ),
        registry=registry, flight=flight,
    )
    # the fleet SLO: TTFT over every engine in the shared registry —
    # the flood's queueing pushes it over the (bucket-edge) threshold,
    # the short windows make the burn measurable within the bench
    fast_window_s, slow_window_s = 5.0, 10.0
    watchdog = SloWatchdog(
        [LatencyObjective(
            "fleet_ttft", "unionml_engine_ttft_ms",
            threshold_ms=ttft_threshold_ms, target=0.5, min_events=4,
            fast_burn=1.0, slow_burn=1.0,
        )],
        registry=registry,
        fast_window_s=fast_window_s, slow_window_s=slow_window_s,
    )
    aux_engines = []

    def factory():
        engine = make_engine()
        engine.warmup(params)   # a joiner must never serve cold compiles
        aux_engines.append(engine)
        return engine, params

    auto = FleetAutoscaler(
        router,
        EngineReplicaProvisioner(factory),
        policy=AutoscalerPolicy(
            min_replicas=2, max_replicas=4,
            fast_burn_threshold=1.0, slow_burn_threshold=1.0,
            sustain_evals=2,
            headroom_out=0.0,          # burn is THE out trigger here
            headroom_in=0.5,
            cooldown_out_s=2.0, cooldown_in_s=0.5,
            warm_blocks=64, reap_unhealthy_evals=2,
        ),
        slo=watchdog, usage=ledger,
        registry=registry, flight=flight,
    )
    rng = np.random.default_rng(0)
    shared = rng.integers(1, cfg.vocab_size, 16).tolist()
    distinct = [
        shared + rng.integers(1, cfg.vocab_size, 8).tolist()
        for _ in range(6)
    ]
    try:
        for e in engines:
            e.warmup(params)
        solo = {
            tuple(p): engines[0].generate(params, [p])[0] for p in distinct
        }
        # prime the SURVIVOR's cache so the first (repair) join always
        # has a warm donor — in production the fleet has served for
        # hours before a scale event; the oracle above only warmed r0
        engines[1].generate(params, [distinct[0]])
        for e in engines:
            e.reset_stats()
        ledger.reset_stats()

        results, failures, lock = [], [], threading.Lock()
        started = threading.Event()

        def client(idx):
            for j in range(n_req // clients):
                p = distinct[(idx + j) % len(distinct)]
                if idx == 0 and j == 1:
                    started.set()
                try:
                    out = router.generate(p)
                    with lock:
                        results.append((tuple(p), out))
                except BaseException as exc:   # EVERY failure counts
                    with lock:
                        failures.append(f"{type(exc).__name__}: {exc}")

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(clients)
        ]
        flood_t0 = time.perf_counter()
        for t in threads:
            t.start()
        started.wait(timeout=120)

        scale_out_s = None
        trigger_s = None
        warm_hit_tokens = 0
        killed = False
        deadline = time.perf_counter() + 600.0
        while any(t.is_alive() for t in threads):
            if time.perf_counter() > deadline:
                raise AssertionError("flood did not complete")
            decision = auto.evaluate()
            if trigger_s is None and decision.get("burn_streak", 0) >= 1:
                # burn DETECTED (the multiwindow trigger is arming) —
                # the fast-window bar applies here; the action latency
                # additionally pays the synchronous provision+warmup
                trigger_s = time.perf_counter() - flood_t0
            if decision["decision"] == "scale_out" and scale_out_s is None:
                scale_out_s = time.perf_counter() - flood_t0
                assert decision["reason"] == "slo_burn", decision
                assert decision["warmed_blocks"] > 0, (
                    f"join was not fleet-warmed: {decision}"
                )
                # the joiner's FIRST request: a shared-prefix prompt
                # straight into the fresh engine. Its cache holds ONLY
                # imported blocks at this instant (its own inserts need
                # a completed request), so any prefill tokens saved
                # here are warm-join hits by construction.
                joiner = aux_engines[-1]
                saved0 = joiner.prefix_cache.stats()["prefill_tokens_saved"]
                probe = shared + rng.integers(1, cfg.vocab_size, 8).tolist()
                probe_out = joiner.generate(params, [probe])[0]
                warm_hit_tokens = (
                    joiner.prefix_cache.stats()["prefill_tokens_saved"]
                    - saved0
                )
                assert warm_hit_tokens > 0, (
                    "joiner's first request missed the warm prefix"
                )
                assert probe_out == engines[1].generate(params, [probe])[0]
                # mid-run KILL: wait for r0 to hold resident streams
                # (the kill must be caller-visible-but-absorbed, never
                # a free idle-replica removal), then its in-flight
                # batch dies OOM-shaped and the replica reads as dead
                k_deadline = time.perf_counter() + 60.0
                busy = 0
                while time.perf_counter() < k_deadline:
                    with engines[0]._lock:
                        busy = sum(
                            r is not None for r in engines[0]._occupant
                        )
                    if busy:
                        break
                    time.sleep(0.002)
                assert busy, "victim replica never took residents"
                fi0.arm("engine.dispatch", exc=xla_oom_error())
                replicas[0].kill()
                killed = True
            time.sleep(0.02)
        for t in threads:
            t.join(timeout=120)
        flood_s = time.perf_counter() - flood_t0

        assert scale_out_s is not None, "the flood never triggered scale-out"
        assert trigger_s is not None and trigger_s <= fast_window_s, (
            f"burn detection took {trigger_s}s — outside the "
            f"{fast_window_s:.0f}s SLO fast window"
        )
        # the action = detection + sustain + synchronous provision &
        # warmup (XLA compiles); generous allowance so CI hosts pass
        assert scale_out_s <= fast_window_s + 15.0, (
            f"scale-out took {scale_out_s:.1f}s — detection "
            f"{trigger_s:.1f}s plus an implausible provision time"
        )
        assert killed
        assert not failures, (
            f"{len(failures)} caller-visible failures (want 0): "
            f"{sorted(set(failures))[:3]}"
        )
        bad = sum(1 for key, out in results if out != solo[key])
        assert bad == 0, f"{bad}/{len(results)} responses lost token parity"
        assert len(results) == n_req

        # the corpse is reaped and replaced; then the idle fleet
        # consolidates back to baseline through the hysteresis band
        settle_deadline = time.perf_counter() + 60.0
        while time.perf_counter() < settle_deadline:
            auto.evaluate()
            members = router.health()["replicas"]
            if "r0" not in members and len(members) <= 2 and all(
                m["state"] == "live" for m in members.values()
            ):
                break
            time.sleep(0.05)
        members = router.health()["replicas"]
        assert "r0" not in members, f"corpse not reaped: {members}"
        assert len(members) == 2, f"did not scale back in: {members}"
        assert router.health()["live_replicas"] == 2

        kinds = [e["kind"] for e in flight.dump()]
        for kind in ("scale_out", "scale_reap", "scale_in", "retry"):
            assert kind in kinds, f"missing {kind} in flight record"
        decisions = {
            values: int(child.value)
            for values, child in auto._m_decisions.children()
        }
        # the burn-driven growth AND the post-kill replacement both
        # provisioned (the replacement rides whichever trigger is hot:
        # still-burning SLO, or the below-min repair after the reap)
        n_scale_outs = sum(
            v for (d, _r), v in decisions.items() if d == "scale_out"
        )
        assert n_scale_outs >= 2, decisions
        assert decisions.get(("scale_out", "slo_burn"), 0) >= 1, decisions
        print(json.dumps({
            "metric": "serve_autoscale",
            "offered": n_req,
            "clients": clients,
            "completed": len(results),
            "caller_visible_failures": len(failures),
            "token_parity": "exact",
            "flood_s": round(flood_s, 2),
            "burn_detect_latency_s": round(trigger_s, 2),
            "scale_out_latency_s": round(scale_out_s, 2),
            "slo_fast_window_s": fast_window_s,
            "warm_join_hit_tokens": int(warm_hit_tokens),
            "warmed_blocks_total": int(auto._m_warmed.value),
            "reaped": int(auto._m_reaped.value),
            "final_replicas": len(members),
            "decisions": {"|".join(k): v for k, v in decisions.items()},
            "unit": "requests",
        }))
    finally:
        auto.close()
        for e in engines + aux_engines:
            e.close()


def disagg_leg() -> None:
    """Disaggregated prefill/decode serving
    (``UNIONML_TPU_BENCH_PRESET=serve_disagg``;
    docs/serving.md "Disaggregated serving").

    Phase 1 — **colocated vs disaggregated on identical hardware**
    under MIXED long/short-prompt traffic: two fleets of two engines
    each — colocated (both serve everything, plain ``FleetRouter``)
    vs phase-split (one prefill + one decode engine sharing a host
    block store, ``DisaggRouter``). Long-prompt clients loop chunked-
    prefill streams for continuous pressure while short-prompt clients
    measure streaming TTFT (call → first chunk). Colocated, a short
    prompt behind a long admission waits out the whole chunked prefill
    (admissions serialize) and the long chunks steal dispatcher passes
    from its decode; disaggregated, long prefills live on the prefill
    engine and the decode engine admits shorts at a flat cadence.

    Estimator protocol (PR 8/13 lineage): per-short-request MIN over
    rounds (each round fully contended — the long loop runs the whole
    sweep), nearest-rank p99 across requests computed UNROUNDED, and
    the headline is the MEDIAN OF THREE independent sweeps per leg.
    Bars: disaggregated short-TTFT p99 strictly beats colocated;
    decode tokens/s (all tokens harvested / sweep wall) no worse than
    0.9x colocated (the noise floor of GIL-scheduled CPU fleets — on
    real hardware the pools are separate chips); every completion
    bit-identical to the solo oracle; 0 caller-visible failures.

    Phase 2 — **chaos mid-handoff**: on the disaggregated fleet, the
    prefill replica is killed between one request's KV export and its
    decode-side splice (export hook dies + the engine OOM-poisoned),
    then a follow-up burst runs against the dead prefill pool. Asserts
    zero caller-visible failures, exact token parity, and lease/pool
    refcounts back to baseline — degrade, never error.
    """
    import gc
    import statistics
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu import telemetry
    from unionml_tpu.models import Llama, make_generator
    from unionml_tpu.serving.disagg import DisaggRouter
    from unionml_tpu.serving.engine import DecodeEngine
    from unionml_tpu.serving.faults import FaultInjector, xla_oom_error
    from unionml_tpu.serving.prefix_cache import RadixPrefixCache
    from unionml_tpu.serving.router import (
        EngineReplica, FleetRouter, RouterPolicy,
    )

    from unionml_tpu.models import LlamaConfig

    backend = jax.default_backend()
    if backend == "cpu":
        # max_len widened so the long bucket holds a genuinely long
        # chunked prefill (14 lead chunks — the interference source)
        cfg = LlamaConfig.tiny(vocab_size=256, max_len=512)
        module = Llama(cfg)
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
        short_n, rounds, sweeps = 12, 3, 3
        long_clients, n_long, n_new = 3, 6, 16
        buckets, chunk, chunk_steps = (16, 256), 16, 4
        # equal slot budget per fleet (6): colocated splits it evenly;
        # the phase-split fleet shapes it to the phases — decode
        # batches wide (memory-bound), prefill barely needs residency
        # at all (a prefill leg occupies its slot only until the first
        # harvest — the DistServe asymmetry)
        colo_slots, prefill_slots, decode_slots = 3, 1, 5
        short_len, long_len = 8, 224
    else:
        cfg = serving_config("serve_1p5b")
        module = Llama(cfg)
        params = random_quantized_params(module)
        short_n, rounds, sweeps = 24, 3, 3
        long_clients, n_long, n_new = 4, 8, 32
        buckets, chunk, chunk_steps = (64, 2048), 64, 8
        colo_slots, prefill_slots, decode_slots = 6, 4, 8
        short_len, long_len = 48, 1536

    rng = np.random.default_rng(0)
    shorts = [
        rng.integers(1, cfg.vocab_size, short_len).tolist()
        for _ in range(short_n)
    ]
    # the solo oracle's cache length must MATCH the engines'
    # (engine.cache_len): attention over a differently-sized masked
    # cache is bf16-numerically different, and at 200+-token random-
    # weight prompts ~5% of requests sit on a near-tie argmax that
    # flips — a mismatched oracle reads that as lost token parity
    # (root-caused in this bench's first run: engine == generator at
    # equal max_len, 0/40; generators at 272 vs 308 rows disagree on
    # exactly the requests the engine "failed"). `gen` binds lazily,
    # after the first fleet reports its cache_len.
    gen = None

    def solo_run(p):
        return np.asarray(
            gen(params, jnp.asarray([p], jnp.int32))
        )[0].tolist()

    from unionml_tpu.serving.scheduler import SchedulerConfig

    def build_engine(phase, cache, reg, slots, fi=None, mix=None,
                     eng_chunk=None):
        # per-pool tuning — the freedom disaggregation buys, and what
        # the colocated baseline structurally cannot copy:
        # - the COLOCATED engines run a FINE prefill chunk (the
        #   TTFT-optimal colocated config: long admissions yield to
        #   the decode lane every `chunk` tokens — coarser chunks
        #   would stall their own residents harder);
        # - the DECODE pool runs a COARSE chunk + a matching mixing
        #   budget (docs/robustness.md, the Sarathi knob — splices
        #   are budget-free): its long admissions are warm SPLICES,
        #   so a whole decode-leg admission collapses to ~4 cheap
        #   dispatches in one pass instead of 15 serialized ones;
        # - the PREFILL pool runs a prefill-sized budget — it has no
        #   decode lane to protect at all.
        # Bucket geometry stays identical across every engine (both
        # chunks divide the long bucket), so the solo oracle and
        # token parity are shared.
        return DecodeEngine(
            module, slots=slots, max_new_tokens=n_new,
            prompt_buckets=buckets,
            prefill_chunk=eng_chunk if eng_chunk is not None else chunk,
            chunk_steps=chunk_steps, prefix_cache=cache, phase=phase,
            registry=reg, fault_injector=fi, paged=True,
            scheduler=SchedulerConfig(
                mix_prefill_tokens=mix if mix is not None else chunk,
            ),
        )

    def run_sweeps(router, engines, label, seed_base):
        """Three sweeps; each: long clients stream a continuous
        sequence of DISTINCT prompts (real long-context traffic —
        repeats would warm the prefix cache and erase the prefill
        pressure) while the short set replays `rounds` times with
        per-request-min TTFT. Long parity is verified post-hoc
        against lazily computed solo oracles (every served long,
        exact). Returns medians over the sweeps."""
        p99s, tps, failures = [], [], []
        long_served = []
        for sweep in range(sweeps):
            for e in engines:
                e.reset_stats()
            stop = threading.Event()
            long_tokens = []

            def long_client(seed):
                crng = np.random.default_rng(seed)
                while not stop.is_set():
                    p = crng.integers(
                        1, cfg.vocab_size, long_len,
                    ).tolist()
                    try:
                        out = []
                        for c in router.generate_stream(p):
                            out.extend(c)
                        long_served.append((tuple(p), out))
                        long_tokens.append(len(out))
                    except BaseException as exc:
                        failures.append(f"long: {type(exc).__name__}")
                        return

            lts = [
                threading.Thread(
                    target=long_client,
                    args=(seed_base + sweep * long_clients + i,),
                )
                for i in range(long_clients)
            ]
            ttft_min = [math.inf] * short_n
            short_tokens = [0]
            gc_was = gc.isenabled()
            gc.disable()
            t_sweep0 = time.perf_counter()
            for t in lts:
                t.start()
            try:
                for _ in range(rounds):
                    for i, p in enumerate(shorts):
                        try:
                            t0 = time.perf_counter()
                            stream = router.generate_stream(p)
                            out = []
                            for j, c in enumerate(stream):
                                if j == 0:
                                    dt = time.perf_counter() - t0
                                    ttft_min[i] = min(ttft_min[i], dt)
                                out.extend(c)
                            if out != solo[tuple(p)]:
                                failures.append("short token mismatch")
                            short_tokens[0] += len(out)
                        except BaseException as exc:
                            failures.append(
                                f"short: {type(exc).__name__}"
                            )
            finally:
                stop.set()
                for t in lts:
                    t.join(timeout=120)
                if gc_was:
                    gc.enable()
            wall = time.perf_counter() - t_sweep0
            v = sorted(ttft_min)
            p99 = v[max(0, math.ceil(0.99 * len(v)) - 1)]  # UNROUNDED
            p99s.append(p99)
            tps.append((short_tokens[0] + sum(long_tokens)) / wall)
        # exact parity for EVERY served long (prompts are distinct, so
        # this is one solo oracle run per long request)
        for key, out in long_served:
            if out != solo.setdefault(key, solo_run(list(key))):
                failures.append("long token mismatch")
        return (
            statistics.median(p99s), statistics.median(tps),
            failures, p99s, tps, len(long_served),
        )

    # ---- colocated fleet: 2 engines, both serve everything ----------
    reg_c = telemetry.MetricsRegistry()
    colo_engines = [
        build_engine(
            "colocated", RadixPrefixCache(registry=reg_c), reg_c,
            colo_slots,
        )
        for _ in range(2)
    ]
    colo = FleetRouter(
        [
            EngineReplica(colo_engines[i], params, name=f"c{i}")
            for i in range(2)
        ],
        policy=RouterPolicy(health_ttl_s=0.05),
        registry=reg_c, flight=telemetry.FlightRecorder(),
    )
    # the oracle, at the engines' exact cache geometry (see above) —
    # slots don't enter cache_len, so every engine in BOTH fleets
    # shares it (asserted when the disagg fleet builds)
    oracle_len = colo_engines[0].cache_len
    gen = make_generator(module, max_new_tokens=n_new, max_len=oracle_len)
    solo = {tuple(p): solo_run(p) for p in shorts}
    try:
        for e in colo_engines:
            e.warmup(params)
        (colo_p99, colo_tps, colo_fail, colo_p99s, colo_tpss,
         colo_longs) = run_sweeps(colo, colo_engines, "colocated", 10_000)
    finally:
        for e in colo_engines:
            e.close()
    assert not colo_fail, colo_fail[:3]

    # ---- disaggregated fleet: 1 prefill + 1 decode, one store ------
    reg_d = telemetry.MetricsRegistry()
    store = RadixPrefixCache(registry=reg_d)
    fi = FaultInjector()
    coarse = chunk * 4
    pre = build_engine(
        "prefill", store, reg_d, prefill_slots, fi, mix=buckets[-1],
        eng_chunk=coarse,
    )
    dec = build_engine(
        "decode", store, reg_d, decode_slots, mix=coarse,
        eng_chunk=coarse,
    )
    disagg = DisaggRouter(
        [EngineReplica(pre, params, name="p0"),
         EngineReplica(dec, params, name="d0")],
        handoff_min_tokens=buckets[0] + 1,  # shorts stay single-leg
        policy=RouterPolicy(
            health_ttl_s=0.05, backoff_base_s=0.001, jitter_s=0.0,
        ),
        registry=reg_d, flight=telemetry.FlightRecorder(),
    )
    try:
        for e in (pre, dec):
            # one oracle serves both fleets only because the cache
            # geometry is identical — a drifted knob would silently
            # turn tie-flips into "parity failures" again
            assert e.cache_len == oracle_len, (e.cache_len, oracle_len)
            e.warmup(params)
        (dis_p99, dis_tps, dis_fail, dis_p99s, dis_tpss,
         dis_longs) = run_sweeps(disagg, (pre, dec), "disagg", 20_000)
        assert not dis_fail, dis_fail[:3]

        print(json.dumps({
            "metric": "serve_disagg_short_ttft_p99_ms",
            "colocated": round(colo_p99 * 1e3, 3),
            "disaggregated": round(dis_p99 * 1e3, 3),
            "value": round(dis_p99 * 1e3, 3),
            "sweeps_colocated_ms": [round(x * 1e3, 3) for x in colo_p99s],
            "sweeps_disagg_ms": [round(x * 1e3, 3) for x in dis_p99s],
            "speedup": round(colo_p99 / max(dis_p99, 1e-9), 2),
            "unit": "ms",
        }))
        print(json.dumps({
            "metric": "serve_disagg_decode_tokens_per_sec",
            "colocated": round(colo_tps, 1),
            "disaggregated": round(dis_tps, 1),
            "value": round(dis_tps, 1),
            "ratio": round(dis_tps / max(colo_tps, 1e-9), 3),
            "long_requests": {"colocated": colo_longs,
                              "disaggregated": dis_longs},
            "unit": "tokens/s",
        }))
        assert dis_p99 < colo_p99, (
            f"disaggregated short TTFT p99 {dis_p99 * 1e3:.2f} ms does "
            f"not beat colocated {colo_p99 * 1e3:.2f} ms"
        )
        assert dis_tps >= 0.9 * colo_tps, (
            f"decode throughput regressed: {dis_tps:.1f} vs colocated "
            f"{colo_tps:.1f} tokens/s (bar: >= 0.9x, the CPU fleet "
            "noise floor)"
        )

        # ---- phase 2: prefill replica killed mid-handoff -----------
        p0 = disagg.replica_handle("p0")
        orig_export = p0.export_request_blocks

        def export_and_die(prompt):
            entries = orig_export(prompt)
            # the kill window: KV exported, splice not yet — the
            # prefill engine OOM-poisons and every later prefill-pool
            # call fails
            fi.arm("engine.prefill", exc=xla_oom_error())
            p0.prefill_export = lambda *a, **k: (
                (_ for _ in ()).throw(RuntimeError("prefill dead"))
            )
            p0.export_request_blocks = lambda *a, **k: (
                (_ for _ in ()).throw(RuntimeError("prefill dead"))
            )
            raise RuntimeError("prefill process died mid-handoff")

        # force the long path two-leg so the handoff actually fires
        p0.export_request_blocks = export_and_die
        # distinct stores now, or the shared store would hide the kill
        dec.prefix_cache = RadixPrefixCache(registry=reg_d)
        disagg.transfer = True
        crng = np.random.default_rng(99)
        chaos_prompts = [
            crng.integers(1, cfg.vocab_size, long_len).tolist()
            for _ in range(3)
        ] + shorts[:4]
        chaos_fail, chaos_done = [], []
        for p in chaos_prompts:
            try:
                out = []
                for c in disagg.generate_stream(p):
                    out.extend(c)
                if out != solo.setdefault(tuple(p), solo_run(p)):
                    chaos_fail.append("token mismatch")
                chaos_done.append(tuple(p))
            except BaseException as exc:
                chaos_fail.append(f"{type(exc).__name__}: {exc}")
        assert not chaos_fail, chaos_fail[:3]
        assert len(chaos_done) == len(chaos_prompts)

        # lease/pool refcounts back to baseline on the survivor
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            s = dec.kv_pool.stats()
            if s["blocks_in_use"] == 0 and s["blocks_reserved"] == 0:
                break
            time.sleep(0.05)
        s = dec.kv_pool.stats()
        assert s["blocks_in_use"] == 0 and s["blocks_reserved"] == 0, s
        leaked = []
        for cache in (dec.prefix_cache, store):
            stack = list(cache._root.children.values())
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if node.refcount != 0:
                    leaked.append(node.refcount)
        assert not leaked, f"leaked lease refcounts: {leaked}"
        print(json.dumps({
            "metric": "serve_disagg_chaos",
            "requests": len(chaos_done),
            "caller_visible_failures": 0,
            "token_parity": "exact",
            "lease_refcounts": "baseline",
            "pool_blocks": "baseline",
        }))
        print(json.dumps({
            "metric": "serve_disagg_summary",
            "short_ttft_p99_speedup": round(
                colo_p99 / max(dis_p99, 1e-9), 2
            ),
            "decode_tps_ratio": round(dis_tps / max(colo_tps, 1e-9), 3),
            "chaos": "0 caller-visible failures, parity exact",
        }))
    finally:
        pre.close()
        dec.close()


def fleet_obs_leg() -> None:
    """Fleet observability plane
    (``UNIONML_TPU_BENCH_PRESET=serve_fleet_obs``;
    docs/observability.md "Fleet observability").

    Phase 1 — **the plane under load**: a 3-replica engine fleet
    behind a router with cross-hop trace stitching ON, concurrent
    clients streaming requests while a background scraper hammers the
    federated ``/metrics`` merge. Asserts ZERO caller-visible
    failures, exact token parity vs the solo oracle, every replica's
    series present under its ``replica`` label in the federated body,
    and a probe request's stitched timeline complete (route root,
    pick/attempt spans, engine timelines parented under the attempt
    that dispatched them, one trace id).

    Phase 2 — **plane overhead**: the same fleet serves the same
    requests with the plane OFF (``router.tracer = None``) and ON,
    paired PER REQUEST in alternating order (the PR 8 estimator
    protocol: whole-pass legs drift percents at minute scale; pairing
    cancels it), per-request MIN over rounds, nearest-rank p99
    computed UNROUNDED over enough requests that the p99 is not the
    sample max — and the bar held against the MEDIAN of three
    independent sweeps (a single 120×20 order statistic still swings
    ~±1.5% from thread-scheduling jitter; measured medians 0.4–1.0%
    across solo runs). The scraper stops first — federation is
    scrape-path work that never rides a request, and a scrape landing
    inside one leg of a pair is exactly the tail noise pairing exists
    to cancel. Asserts ≤ 2% p99 and bit-identical tokens on both
    legs.
    """
    import gc
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu import telemetry
    from unionml_tpu.models import Llama
    from unionml_tpu.serving.engine import DecodeEngine
    from unionml_tpu.serving.router import (
        EngineReplica, FleetRouter, RouterPolicy, make_router_app,
    )

    backend = jax.default_backend()
    if backend == "cpu":
        cfg = serving_config("tiny")
        module = Llama(cfg)
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
        n_req, clients, slots = 48, 6, 2
        new_tokens, bucket, chunk_steps = 16, 16, 4
        overhead_reqs, overhead_rounds = 40, 6
    else:
        cfg = serving_config("serve_1p5b")
        module = Llama(cfg)
        params = random_quantized_params(module)
        n_req, clients, slots = 192, 24, 8
        new_tokens, bucket, chunk_steps = 32, 64, 8
        overhead_reqs, overhead_rounds = 120, 8

    n_replicas = 3
    # estimator hardening (the PR 8 lessons, plus this preset's own
    # measured spread): 120+ requests so nearest-rank p99 is the
    # 2nd-worst min rather than the sample max, and 20 rounds on CPU —
    # at 10 rounds the per-request min still carries ±3-5% of harvester
    # thread-scheduling jitter at the p99, swamping a 2% bar (measured:
    # 10-round runs spread -6.5%..+5.6%, 20-round runs -0.1%..+1.8%)
    overhead_reqs = max(overhead_reqs, 120)
    if backend == "cpu":
        overhead_rounds = max(overhead_rounds, 20)
    tracer = telemetry.TraceRecorder()
    app_registry = telemetry.MetricsRegistry()
    flight = telemetry.FlightRecorder()
    # per-engine registries: the federation merge has real per-replica
    # bodies to label (the shared-registry path is the degenerate case)
    engines = [
        DecodeEngine(
            module, slots=slots, max_new_tokens=new_tokens,
            prompt_buckets=(bucket,), chunk_steps=chunk_steps,
            max_queue_depth=64, registry=telemetry.MetricsRegistry(),
            tracer=tracer,
        )
        for _ in range(n_replicas)
    ]
    router = FleetRouter(
        [
            EngineReplica(engines[i], params, name=f"r{i}")
            for i in range(n_replicas)
        ],
        policy=RouterPolicy(health_ttl_s=0.05),
        registry=app_registry,
        flight=flight,
        tracer=tracer,
    )
    app = make_router_app(
        router, registry=app_registry, tracer=tracer, flight=flight,
    )
    rng = np.random.default_rng(0)
    distinct = [
        rng.integers(1, cfg.vocab_size, bucket // 2).tolist()
        for _ in range(8)
    ]
    scrape_stop = threading.Event()
    scrape_bodies = [0]

    def scraper():
        while not scrape_stop.is_set():
            body = app.metrics_text()
            if 'replica="r0"' in body:
                scrape_bodies[0] += 1
            scrape_stop.wait(0.05)

    scraper_thread = threading.Thread(target=scraper, daemon=True)
    try:
        for e in engines:
            e.warmup(params)
        solo = {
            tuple(p): engines[0].generate(params, [p])[0] for p in distinct
        }
        scraper_thread.start()

        # ---- phase 1: loaded run, plane ON ----
        results, failures, lock = [], [], threading.Lock()

        def client(idx):
            for p in (
                distinct[(idx + k) % len(distinct)]
                for k in range(n_req // clients)
            ):
                try:
                    out = router.generate(p)
                    with lock:
                        results.append((tuple(p), out))
                except BaseException as exc:  # EVERY failure counts
                    with lock:
                        failures.append(f"{type(exc).__name__}: {exc}")

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in threads), "clients hung"
        assert not failures, (
            f"{len(failures)} caller-visible failures (want 0): "
            f"{sorted(set(failures))[:3]}"
        )
        bad = sum(1 for key, out in results if out != solo[key])
        assert bad == 0, f"{bad}/{len(results)} responses lost token parity"

        # a probe STREAMING request right after the flood: its routing
        # timeline is now deterministically the NEWEST route timeline,
        # and the stitched-timeline acceptance rides it
        probe_prompt = distinct[0]
        probe_tokens = [
            t for c in router.generate_stream(probe_prompt) for t in c
        ]
        assert probe_tokens == solo[tuple(probe_prompt)]
        probe_rid = next(
            rid_done
            for rid_done, meta_done, _ in reversed(tracer._done)
            if meta_done.get("kind") == "route"
        )
        # the probe's engine timeline retires on the harvester thread
        # moments after the stream's last chunk: bounded wait
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            doc, _ = app.debug_trace(rid=probe_rid)
            if any(
                s.get("root") and s["kind"] == "stream"
                for s in doc["spans"]
            ):
                break
            time.sleep(0.01)

        body = app.metrics_text()
        for i in range(n_replicas):
            assert f'replica="r{i}"' in body, (
                f"federated body is missing replica r{i}"
            )
        assert "unionml_router_requests_total" in body
        assert scrape_bodies[0] > 0, "no federated scrape completed"

        doc, _ = app.debug_trace(rid=probe_rid)
        assert doc["trace_id"], "probe request has no stitched trace"
        span_names = [s["name"] for s in doc["spans"]]
        assert "route" in span_names and "pick" in span_names, span_names
        attempts = {
            s["span_id"] for s in doc["spans"] if s["name"] == "attempt"
        }
        stream_roots = [
            s for s in doc["spans"]
            if s.get("root") and s["kind"] == "stream"
        ]
        assert stream_roots, "engine timeline missing from the stitch"
        assert all(
            s["parent_span_id"] in attempts for s in stream_roots
        ), "engine timelines not parented under the dispatch attempt"
        print(json.dumps({
            "metric": "serve_fleet_obs_plane_under_load",
            "replicas": n_replicas,
            "offered": n_req + 1,
            "completed": len(results) + 1,
            "caller_visible_failures": len(failures),
            "federated_scrapes": scrape_bodies[0],
            "stitched_spans": len(doc["spans"]),
            "token_parity": "exact",
            "unit": "requests",
        }))

        # ---- phase 2: paired per-request plane on/off overhead ----
        # the scraper stops first: federation is scrape-path work (its
        # merge cost never rides a request), and a background scrape
        # landing inside one leg of a pair is exactly the tail noise
        # the paired protocol exists to cancel
        scrape_stop.set()
        scraper_thread.join(timeout=5.0)
        prompts = [
            rng.integers(1, cfg.vocab_size, bucket // 2).tolist()
            for _ in range(overhead_reqs)
        ]

        def p99(vals):  # nearest-rank, UNROUNDED (0.1 ms rounding is
            v = sorted(vals)  # percents of this workload)
            return v[max(0, math.ceil(0.99 * len(v)) - 1)]

        def sweep(sweep_i):
            """One full paired measurement; even a 120×20 min-of-rounds
            p99 still swings ~±1.5% from thread-scheduling jitter on a
            CPU host, so the BAR is held against the median of three
            independent sweeps — the single-order-statistic estimate
            is the noise, not the plane."""
            off_min = [math.inf] * overhead_reqs
            on_min = [math.inf] * overhead_reqs
            token_mismatch = 0
            gc_was_enabled = gc.isenabled()
            gc.disable()
            try:
                for r in range(overhead_rounds):
                    for i, p in enumerate(prompts):
                        legs = [("off", i), ("on", i)]
                        if (r + i + sweep_i) % 2:
                            legs.reverse()  # drift cancels in the pair
                        outs = {}
                        for legname, idx in legs:
                            router.tracer = (
                                tracer if legname == "on" else None
                            )
                            t0 = time.perf_counter()
                            out = router.generate(p)
                            dt = time.perf_counter() - t0
                            mins = on_min if legname == "on" else off_min
                            mins[idx] = min(mins[idx], dt)
                            outs[legname] = out
                        if outs["off"] != outs["on"]:
                            token_mismatch += 1
            finally:
                router.tracer = tracer
                if gc_was_enabled:
                    gc.enable()
            assert token_mismatch == 0, (
                f"{token_mismatch} plane-on responses diverged from "
                "plane-off"
            )
            return p99(off_min), p99(on_min)

        sweeps = [sweep(s) for s in range(3)]
        overheads = sorted(
            (on99 - off99) / off99 if off99 > 0 else 0.0
            for off99, on99 in sweeps
        )
        overhead = overheads[1]  # median of 3 independent sweeps
        off99, on99 = sweeps[0]
        assert overhead <= 0.02, (
            f"observability plane adds {overhead:.1%} median p99 "
            f"(sweeps: {', '.join(f'{o:.2%}' for o in overheads)}); "
            "bar is 2%"
        )
        print(json.dumps({
            "metric": "serve_fleet_obs_p99_overhead",
            "requests": overhead_reqs,
            "rounds": overhead_rounds,
            "sweeps": 3,
            "sweep_overheads_pct": [
                round(o * 100, 2) for o in overheads
            ],
            "plane_off_p99_ms": round(off99 * 1e3, 3),
            "plane_on_p99_ms": round(on99 * 1e3, 3),
            "value": round(overhead * 100, 2),
            "token_parity": "exact",
            "unit": "percent",
        }))
        print(json.dumps({
            "metric": "serve_fleet_obs_summary",
            "plane_under_load": "0 caller-visible failures, parity exact",
            "federation": f"{n_replicas} replicas under one scrape",
            "p99_overhead_pct": round(overhead * 100, 2),
        }))
    finally:
        scrape_stop.set()
        scraper_thread.join(timeout=5.0)
        for e in engines:
            e.close()


def perf_leg() -> None:
    """Serving goodput plane overhead + tail attribution
    (``UNIONML_TPU_BENCH_PRESET=serve_perf``; docs/observability.md
    "Serving goodput & tail attribution").

    Phase 1 — **the plane live**: a single-replica router fleet (the
    engine, router app, and plane share one registry/flight/tracer, so
    the tail endpoints resolve without federation) serves a concurrent
    flood with the goodput plane ON. Asserts ZERO caller-visible
    failures, exact token parity vs the solo oracle, a sane
    fleet-merged ``/debug/goodput`` (ratios recomputed on summed
    slot-step ledgers, goodput in (0, 1]), and a populated per-token
    ITL histogram.

    Phase 2 — **plane overhead**: the same requests with the plane OFF
    and ON, paired PER REQUEST in alternating order on the SAME engine
    instance via the ``engine.perf`` setter seam (two
    separately-constructed engines differ by several percent from
    thread/allocator placement alone, swamping a 1% bar). Flight ring
    and tracer stay ON in both legs — only the goodput plane toggles,
    so the delta is the plane's own cost. Same paired estimator as the
    fleet-obs leg — per-request MIN over rounds, nearest-rank p99
    computed UNROUNDED, three independent sweeps — but the BAR is held
    against the p99 of the per-request mins POOLED across all three
    sweeps rather than the median of per-sweep p99s: the plane's
    measured cost (~26 us/request, ~0.3% of a tiny-model CPU request)
    sits an order of magnitude below the per-sweep p99's own
    scheduling noise on the 1-core host (measured per-sweep deltas
    swing ±2-7% while the pooled estimate settles at +0.4-0.9% from
    32 pooled rounds on), so the median-of-3 verdict would be a coin
    flip about the host, not the plane. Per-sweep overheads and their
    median are still reported as diagnostics. Asserts <= 1% pooled p99
    and bit-identical tokens, and per sweep runs one streaming tail
    probe whose decode exemplar resolves ``/debug/tail`` → per-phase
    segments → ``/debug/trace`` (histogram bucket to stitched timeline
    in one hop).
    """
    import gc
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu import telemetry
    from unionml_tpu.models import Llama
    from unionml_tpu.serving.engine import DecodeEngine
    from unionml_tpu.serving.router import (
        EngineReplica, FleetRouter, RouterPolicy, make_router_app,
    )

    backend = jax.default_backend()
    if backend == "cpu":
        cfg = serving_config("tiny")
        module = Llama(cfg)
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
        n_req, clients, slots = 48, 6, 2
        new_tokens, bucket, chunk_steps = 16, 16, 4
        overhead_reqs, overhead_rounds = 40, 6
    else:
        cfg = serving_config("serve_1p5b")
        module = Llama(cfg)
        params = random_quantized_params(module)
        n_req, clients, slots = 192, 24, 8
        new_tokens, bucket, chunk_steps = 32, 64, 8
        overhead_reqs, overhead_rounds = 120, 8

    # same estimator hardening as the fleet-obs leg, and MORE binding
    # here: the bar is 1%, half the fleet-obs bar, while the plane's
    # measured per-request cost is ~26 us (~0.3% of a tiny-model CPU
    # request) — so the verdict hinges on min-over-rounds convergence,
    # not the plane. 32 rounds per sweep × 3 sweeps = 96 pooled tries
    # per request per leg, where the pooled p99 delta was measured
    # stable (+0.4-0.9%); the per-sweep p99s individually still swing
    # ±2-7% on the 1-core host and are reported as diagnostics only
    overhead_reqs = max(overhead_reqs, 120)
    if backend == "cpu":
        overhead_rounds = max(overhead_rounds, 32)
    registry = telemetry.MetricsRegistry()
    flight = telemetry.FlightRecorder()
    tracer = telemetry.TraceRecorder()
    engine = DecodeEngine(
        module, slots=slots, max_new_tokens=new_tokens,
        prompt_buckets=(bucket,), chunk_steps=chunk_steps,
        max_queue_depth=64, registry=registry, flight=flight,
        tracer=tracer,
    )
    router = FleetRouter(
        [EngineReplica(engine, params, name="r0")],
        policy=RouterPolicy(health_ttl_s=0.05),
        registry=registry,
        flight=flight,
        tracer=tracer,
    )
    app = make_router_app(
        router, registry=registry, tracer=tracer, flight=flight,
    )
    plane = engine.perf
    assert plane is not None, (
        "goodput plane should be ON by default while introspect=True"
    )
    rng = np.random.default_rng(0)
    distinct = [
        rng.integers(1, cfg.vocab_size, bucket // 2).tolist()
        for _ in range(8)
    ]
    try:
        engine.warmup(params)
        solo = {tuple(p): engine.generate(params, [p])[0] for p in distinct}

        # ---- phase 1: loaded run, plane ON ----
        results, failures, lock = [], [], threading.Lock()

        def client(idx):
            for p in (
                distinct[(idx + k) % len(distinct)]
                for k in range(n_req // clients)
            ):
                try:
                    out = router.generate(p)
                    with lock:
                        results.append((tuple(p), out))
                except BaseException as exc:  # EVERY failure counts
                    with lock:
                        failures.append(f"{type(exc).__name__}: {exc}")

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in threads), "clients hung"
        assert not failures, (
            f"{len(failures)} caller-visible failures (want 0): "
            f"{sorted(set(failures))[:3]}"
        )
        bad = sum(1 for key, out in results if out != solo[key])
        assert bad == 0, f"{bad}/{len(results)} responses lost token parity"

        goodput = app.debug_goodput()
        fleet = goodput["fleet"]
        assert fleet["replicas"] == 1
        assert sum(fleet["passes"].values()) > 0, "no dispatcher passes"
        assert 0.0 < fleet["goodput_ratio"] <= 1.0, fleet
        assert fleet["occupancy_ratio"] >= fleet["goodput_ratio"], fleet
        assert fleet["tokens"] > 0, fleet
        itl = next(
            f for f in registry.collect()
            if f.name == "unionml_engine_itl_ms"
        )
        itl_n = sum(len(child.samples()) for _, child in itl.children())
        assert itl_n > 0, "per-token ITL histogram is empty under load"
        print(json.dumps({
            "metric": "serve_perf_plane_under_load",
            "offered": n_req,
            "completed": len(results),
            "caller_visible_failures": len(failures),
            "goodput_ratio": fleet["goodput_ratio"],
            "occupancy_ratio": fleet["occupancy_ratio"],
            "itl_observations": itl_n,
            "token_parity": "exact",
            "unit": "requests",
        }))

        # ---- phase 2: paired per-request plane on/off overhead ----
        prompts = [
            rng.integers(1, cfg.vocab_size, bucket // 2).tolist()
            for _ in range(overhead_reqs)
        ]

        def p99(vals):  # nearest-rank, UNROUNDED (0.1 ms rounding is
            v = sorted(vals)  # percents of this workload)
            return v[max(0, math.ceil(0.99 * len(v)) - 1)]

        def tail_probe(sweep_i):
            """One streaming request, then its decode exemplar walked
            /debug/tail → segments → /debug/trace. Runs with the plane
            ON (exemplar capture is plane-gated); the finish event and
            exemplar land on the harvester thread moments after the
            last chunk, so the resolution is a bounded wait."""
            probe = distinct[sweep_i % len(distinct)]
            streams_before = sum(
                1 for _, meta_done, _ in tracer._done
                if meta_done.get("kind") == "stream"
            )
            out = [t for c in router.generate_stream(probe) for t in c]
            assert out == solo[tuple(probe)], "tail probe lost parity"
            deadline = time.monotonic() + 10.0
            row = None
            while time.monotonic() < deadline:
                stream_rids = [
                    rid_done for rid_done, meta_done, _ in tracer._done
                    if meta_done.get("kind") == "stream"
                ]
                if len(stream_rids) > streams_before:
                    rows = app.debug_tail(
                        metric="unionml_engine_decode_ms", n=64,
                    )["requests"]
                    row = next(
                        (
                            r for r in rows
                            if r["rid"] == stream_rids[-1]
                            and "segments" in r
                        ),
                        None,
                    )
                    if row is not None:
                        break
                time.sleep(0.01)
            assert row is not None, (
                "tail probe's decode exemplar never became resolvable "
                "via /debug/tail"
            )
            assert row["segments"]["tokens"] == new_tokens, row
            assert row["segments"]["itl_tokens"] == new_tokens - 1, row
            doc, _ = app.debug_trace(rid=row["rid"])
            assert doc["trace_id"] and doc["spans"], (
                "tail exemplar rid did not resolve in /debug/trace"
            )

        def sweep(sweep_i):
            """One full paired measurement; returns the per-request
            min arrays so the caller can both report this sweep's own
            p99 delta and pool the mins across sweeps for the bar."""
            off_min = [math.inf] * overhead_reqs
            on_min = [math.inf] * overhead_reqs
            token_mismatch = 0
            gc_was_enabled = gc.isenabled()
            gc.collect()  # every sweep starts from the same heap state
            gc.disable()
            try:
                for r in range(overhead_rounds):
                    for i, p in enumerate(prompts):
                        legs = [("off", i), ("on", i)]
                        if (r + i + sweep_i) % 2:
                            legs.reverse()  # drift cancels in the pair
                        outs = {}
                        for legname, idx in legs:
                            # the setter seam: swap only while idle —
                            # requests here are strictly serial
                            engine.perf = (
                                plane if legname == "on" else None
                            )
                            t0 = time.perf_counter()
                            out = router.generate(p)
                            dt = time.perf_counter() - t0
                            mins = on_min if legname == "on" else off_min
                            mins[idx] = min(mins[idx], dt)
                            outs[legname] = out
                        if outs["off"] != outs["on"]:
                            token_mismatch += 1
            finally:
                engine.perf = plane
                if gc_was_enabled:
                    gc.enable()
            assert token_mismatch == 0, (
                f"{token_mismatch} plane-on responses diverged from "
                "plane-off"
            )
            tail_probe(sweep_i)
            return off_min, on_min

        sweeps = [sweep(s) for s in range(3)]
        sweep_overheads = sorted(
            (p99(on_m) - p99(off_m)) / p99(off_m)
            for off_m, on_m in sweeps
        )
        pooled_off = [
            min(off_m[i] for off_m, _ in sweeps)
            for i in range(overhead_reqs)
        ]
        pooled_on = [
            min(on_m[i] for _, on_m in sweeps)
            for i in range(overhead_reqs)
        ]
        off99, on99 = p99(pooled_off), p99(pooled_on)
        overhead = (on99 - off99) / off99 if off99 > 0 else 0.0
        assert overhead <= 0.01, (
            f"goodput plane adds {overhead:.2%} pooled p99 "
            f"(per-sweep: {', '.join(f'{o:.2%}' for o in sweep_overheads)}); "
            "bar is 1%"
        )
        print(json.dumps({
            "metric": "serve_perf_p99_overhead",
            "requests": overhead_reqs,
            "rounds": overhead_rounds,
            "sweeps": 3,
            "sweep_overheads_pct": [
                round(o * 100, 2) for o in sweep_overheads
            ],
            "sweep_overhead_median_pct": round(
                sweep_overheads[1] * 100, 2
            ),
            "plane_off_p99_ms": round(off99 * 1e3, 3),
            "plane_on_p99_ms": round(on99 * 1e3, 3),
            "value": round(overhead * 100, 2),
            "token_parity": "exact",
            "unit": "percent",
        }))
        print(json.dumps({
            "metric": "serve_perf_summary",
            "plane_under_load": "0 caller-visible failures, parity exact",
            "goodput_ratio": fleet["goodput_ratio"],
            "tail_probes_resolved": 3,
            "p99_overhead_pct": round(overhead * 100, 2),
        }))
    finally:
        engine.close()


def rollout_leg() -> None:
    """Zero-downtime model lifecycle under flood
    (``UNIONML_TPU_BENCH_PRESET=serve_rollout``;
    docs/robustness.md "Rollouts & rollback").

    A 2-engine fleet serves continuous background flood plus a
    measured short-request set. Leg 1 measures the STEADY-STATE
    streaming TTFT baseline. Leg 2 repeats the identical measurement
    while a full release lifecycle churns underneath each sweep: a bad
    version (negated weights) is rolled forward, its shadow diffs
    catch the parity regression and auto-roll it back; then a clean
    version rolls forward, bakes through shadow matches, and is
    operator-promoted through rolling drain → bind → rejoin.

    Estimator protocol (PR 8/13 lineage): per-request MIN over rounds
    (each round fully contended), nearest-rank p99 across requests
    computed UNROUNDED, headline = MEDIAN OF THREE sweeps per leg.
    Bars: 0 caller-visible failures across BOTH legs (rollback and
    promotion drains retry inside the router envelope — callers never
    see them); every completed request bit-identical to the solo
    oracle (canary_percent=0: live traffic is never steered onto the
    canary, and shadow dispatches are free-riders); lifecycle-churn
    p99 within 2.0x of steady-state (per-request min absorbs the
    drain windows — the bar says churn costs tail, never availability
    or correctness); after the last sweep the fleet serves the final
    promoted version with the canary pool reaped and the decision
    counters telling the whole story.
    """
    import gc
    import statistics
    import tempfile
    import threading

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu import telemetry
    from unionml_tpu.models import Llama, LlamaConfig, make_generator
    from unionml_tpu.serving.autoscaler import EngineReplicaProvisioner
    from unionml_tpu.serving.engine import DecodeEngine
    from unionml_tpu.serving.prefix_cache import RadixPrefixCache
    from unionml_tpu.serving.rollout import (
        RolloutController, RolloutPolicy, VersionRegistry,
    )
    from unionml_tpu.serving.router import (
        EngineReplica, FleetRouter, RouterPolicy,
    )

    backend = jax.default_backend()
    if backend == "cpu":
        cfg = LlamaConfig.tiny(vocab_size=256)
        module = Llama(cfg)
        tokens0 = jnp.zeros((1, 8), jnp.int32)
        params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
        short_n, rounds, sweeps = 10, 3, 3
        flood_clients, n_new, slots = 2, 8, 4
        buckets, chunk_steps, short_len = (16,), 4, 8
    else:
        cfg = serving_config("serve_1p5b")
        module = Llama(cfg)
        params = random_quantized_params(module)
        short_n, rounds, sweeps = 24, 3, 3
        flood_clients, n_new, slots = 4, 32, 8
        buckets, chunk_steps, short_len = (64,), 8, 48

    # same VALUES, new identity: promotion exercises the full drain →
    # bind → rejoin machinery without changing one emitted token
    params_good = jax.tree_util.tree_map(lambda x: jnp.array(x), params)
    params_bad = jax.tree_util.tree_map(lambda x: -x, params)

    reg = telemetry.MetricsRegistry()
    flight = telemetry.FlightRecorder()

    def make_engine():
        return DecodeEngine(
            module, slots=slots, max_new_tokens=n_new,
            prompt_buckets=buckets, chunk_steps=chunk_steps,
            prefix_cache=RadixPrefixCache(registry=reg), registry=reg,
        )

    engines = [make_engine() for _ in range(2)]
    canary_engines = []

    def factory():
        e = make_engine()
        canary_engines.append(e)
        return e, params

    router = FleetRouter(
        [EngineReplica(engines[i], params, name=f"r{i}") for i in range(2)],
        policy=RouterPolicy(
            health_ttl_s=0.05, backoff_base_s=0.001, jitter_s=0.0,
        ),
        registry=reg, flight=flight,
    )

    vroot = tempfile.mkdtemp(prefix="unionml_rollout_bench_")
    vreg = VersionRegistry(vroot)
    for k in range(1, sweeps + 1):
        vreg.publish(f"bad-{k}", {"w": np.zeros(2, np.float32)})
        vreg.publish(f"good-{k}", {"w": np.ones(2, np.float32)})
    ctl = RolloutController(
        router, EngineReplicaProvisioner(factory), vreg,
        policy=RolloutPolicy(
            canary_replicas=1, canary_percent=0.0, shadow=True,
            shadow_queue=128, bake_evals=2, sustain_evals=2,
            auto_promote=False, warm_blocks=0, drain_timeout_s=60.0,
        ),
        params_loader=lambda v: (
            params_bad if v.startswith("bad") else params_good
        ),
        registry=reg, flight=flight,
    )

    # the solo oracle at the engines' exact cache geometry (the disagg
    # leg's root cause — a padded-length mismatch flips near-tie
    # argmaxes and reads as lost parity)
    oracle_len = engines[0].cache_len
    gen = make_generator(module, max_new_tokens=n_new, max_len=oracle_len)
    rng = np.random.default_rng(7)
    shorts = [
        rng.integers(1, cfg.vocab_size, short_len).tolist()
        for _ in range(short_n)
    ]
    solo = {
        tuple(p): np.asarray(
            gen(params, jnp.asarray([p], jnp.int32))
        )[0].tolist()
        for p in shorts
    }

    failures: list = []

    def run_sweep(churn_version_k=None):
        """One sweep: background flood + measured rounds; when
        ``churn_version_k`` is set, a choreographer thread drives the
        full bad-rollback + good-promote lifecycle underneath."""
        stop = threading.Event()

        def flood_client(seed):
            crng = np.random.default_rng(seed)
            while not stop.is_set():
                p = shorts[int(crng.integers(0, short_n))]
                try:
                    out = router.generate(p)
                    if out != solo[tuple(p)]:
                        failures.append("flood token mismatch")
                except BaseException as exc:
                    failures.append(f"flood: {type(exc).__name__}")
                    return

        def choreograph(k):
            try:
                deadline = time.monotonic() + 120.0
                ctl.start_rollout(f"bad-{k}")
                # provisioning ticks through; then shadow divergences
                # sustain into the automatic rollback
                while time.monotonic() < deadline:
                    d = ctl.dashboard()
                    if d["stage"] == "idle" and any(
                        h["reason"] == "parity_regression"
                        for h in d["history"]
                    ):
                        break
                    time.sleep(0.02)
                else:
                    failures.append("bad version did not roll back")
                    return
                ctl.start_rollout(f"good-{k}")
                while time.monotonic() < deadline:
                    d = ctl.dashboard()
                    if d["stage"] == "baking" and (
                        d["shadow"]["match"] >= 1
                    ):
                        break
                    time.sleep(0.02)
                ctl.promote()
                while time.monotonic() < deadline:
                    if ctl.dashboard()["stage"] == "idle":
                        break
                    time.sleep(0.02)
                if router.live_version != f"good-{k}":
                    failures.append(
                        f"good-{k} did not promote "
                        f"(live={router.live_version})"
                    )
            except BaseException as exc:
                failures.append(f"choreography: {type(exc).__name__}: {exc}")

        flts = [
            threading.Thread(target=flood_client, args=(1000 + i,))
            for i in range(flood_clients)
        ]
        chor = None
        if churn_version_k is not None:
            ctl.start(interval_s=0.05)
            chor = threading.Thread(
                target=choreograph, args=(churn_version_k,)
            )
        ttft_min = [math.inf] * short_n
        gc_was = gc.isenabled()
        gc.disable()
        for t in flts:
            t.start()
        if chor is not None:
            chor.start()
        try:
            done = False
            while not done:
                # keep measuring full rounds until the lifecycle (when
                # one is running) has completed — churn must overlap
                # the measurement window, not straddle past it
                for _ in range(rounds):
                    for i, p in enumerate(shorts):
                        try:
                            t0 = time.perf_counter()
                            stream = router.generate_stream(p)
                            out = []
                            for j, c in enumerate(stream):
                                if j == 0:
                                    dt = time.perf_counter() - t0
                                    ttft_min[i] = min(ttft_min[i], dt)
                                out.extend(c)
                            if out != solo[tuple(p)]:
                                failures.append("short token mismatch")
                        except BaseException as exc:
                            failures.append(
                                f"short: {type(exc).__name__}"
                            )
                done = chor is None or not chor.is_alive()
        finally:
            stop.set()
            for t in flts:
                t.join(timeout=120)
            if chor is not None:
                chor.join(timeout=120)
                ctl.stop()
            if gc_was:
                gc.enable()
        v = sorted(ttft_min)
        return v[max(0, math.ceil(0.99 * len(v)) - 1)]  # UNROUNDED

    try:
        for e in engines:
            e.warmup(params)
        steady_p99s = [run_sweep() for _ in range(sweeps)]
        churn_p99s = [run_sweep(k) for k in range(1, sweeps + 1)]
        assert not failures, failures[:5]
        steady = statistics.median(steady_p99s)
        churn = statistics.median(churn_p99s)
        print(json.dumps({
            "metric": "serve_rollout_ttft_p99_ms",
            "steady": round(steady * 1e3, 3),
            "under_lifecycle_churn": round(churn * 1e3, 3),
            "value": round(churn * 1e3, 3),
            "sweeps_steady_ms": [round(x * 1e3, 3) for x in steady_p99s],
            "sweeps_churn_ms": [round(x * 1e3, 3) for x in churn_p99s],
            "ratio": round(churn / max(steady, 1e-9), 3),
            "unit": "ms",
        }))
        assert churn <= 2.0 * steady, (
            f"lifecycle churn p99 {churn * 1e3:.2f} ms blew the bar "
            f"(2.0x steady-state {steady * 1e3:.2f} ms) — a rollout "
            "must cost tail latency, never availability"
        )
        # the fleet landed on the LAST promoted version with the
        # canary pool reaped and the ledger at baseline
        assert router.live_version == f"good-{sweeps}"
        assert set(router.members()) == {"r0", "r1"}
        assert len(canary_engines) == 2 * sweeps
        snap = reg.snapshot()
        assert snap["unionml_rollout_canary_replicas"] == {"": 0.0}
        decisions = snap["unionml_rollout_decisions_total"]
        rollbacks = sum(
            v for k, v in decisions.items()
            if "reason=parity_regression" in k
        )
        completes = sum(
            v for k, v in decisions.items() if "reason=complete" in k
        )
        assert rollbacks >= sweeps and completes >= sweeps, decisions
        print(json.dumps({
            "metric": "serve_rollout_summary",
            "lifecycles": sweeps,
            "auto_rollbacks": int(rollbacks),
            "promotions": int(completes),
            "caller_visible_failures": 0,
            "token_parity": "exact",
            "live_version": router.live_version,
        }))
    finally:
        ctl.close()
        vreg.close()
        router.close()
        for e in engines + canary_engines:
            e.close()


if __name__ == "__main__":
    if os.environ.get("UNIONML_TPU_BENCH_PRESET") == "serve_tracing":
        if len(sys.argv) > 1 or os.environ.get("UNIONML_TPU_BENCH_KV") or (
            os.environ.get("UNIONML_TPU_BENCH_PREFIX")
        ):
            # hardcoded workload, same rule as the other engine legs
            raise SystemExit(
                "UNIONML_TPU_BENCH_PRESET=serve_tracing takes no CLI "
                f"flags or KV/PREFIX env legs (got {sys.argv[1:]}); its "
                "workload is hardcoded in tracing_leg"
            )
        tracing_leg()
    elif os.environ.get("UNIONML_TPU_BENCH_PRESET") == "serve_introspection":
        if len(sys.argv) > 1 or os.environ.get("UNIONML_TPU_BENCH_KV") or (
            os.environ.get("UNIONML_TPU_BENCH_PREFIX")
        ):
            # hardcoded workload, same rule as the other engine legs
            raise SystemExit(
                "UNIONML_TPU_BENCH_PRESET=serve_introspection takes no CLI "
                f"flags or KV/PREFIX env legs (got {sys.argv[1:]}); its "
                "workload is hardcoded in introspection_leg"
            )
        introspection_leg()
    elif os.environ.get("UNIONML_TPU_BENCH_PRESET") == "serve_paged":
        if len(sys.argv) > 1 or os.environ.get("UNIONML_TPU_BENCH_KV") or (
            os.environ.get("UNIONML_TPU_BENCH_PREFIX")
        ):
            # hardcoded workload, same rule as the other engine legs
            raise SystemExit(
                "UNIONML_TPU_BENCH_PRESET=serve_paged takes no CLI "
                f"flags or KV/PREFIX env legs (got {sys.argv[1:]}); its "
                "workload is hardcoded in paged_leg"
            )
        paged_leg()
    elif os.environ.get("UNIONML_TPU_BENCH_PRESET") == "serve_disagg":
        if len(sys.argv) > 1 or os.environ.get("UNIONML_TPU_BENCH_KV") or (
            os.environ.get("UNIONML_TPU_BENCH_PREFIX")
        ):
            # hardcoded workload, same rule as the other engine legs
            raise SystemExit(
                "UNIONML_TPU_BENCH_PRESET=serve_disagg takes no CLI "
                f"flags or KV/PREFIX env legs (got {sys.argv[1:]}); its "
                "workload is hardcoded in disagg_leg"
            )
        disagg_leg()
    elif os.environ.get("UNIONML_TPU_BENCH_PRESET") == "serve_fleet_obs":
        if len(sys.argv) > 1 or os.environ.get("UNIONML_TPU_BENCH_KV") or (
            os.environ.get("UNIONML_TPU_BENCH_PREFIX")
        ):
            # hardcoded workload, same rule as the other engine legs
            raise SystemExit(
                "UNIONML_TPU_BENCH_PRESET=serve_fleet_obs takes no CLI "
                f"flags or KV/PREFIX env legs (got {sys.argv[1:]}); its "
                "workload is hardcoded in fleet_obs_leg"
            )
        fleet_obs_leg()
    elif os.environ.get("UNIONML_TPU_BENCH_PRESET") == "serve_perf":
        if len(sys.argv) > 1 or os.environ.get("UNIONML_TPU_BENCH_KV") or (
            os.environ.get("UNIONML_TPU_BENCH_PREFIX")
        ):
            # hardcoded workload, same rule as the other engine legs
            raise SystemExit(
                "UNIONML_TPU_BENCH_PRESET=serve_perf takes no CLI "
                f"flags or KV/PREFIX env legs (got {sys.argv[1:]}); its "
                "workload is hardcoded in perf_leg"
            )
        perf_leg()
    elif os.environ.get("UNIONML_TPU_BENCH_PRESET") == "serve_rollout":
        if len(sys.argv) > 1 or os.environ.get("UNIONML_TPU_BENCH_KV") or (
            os.environ.get("UNIONML_TPU_BENCH_PREFIX")
        ):
            # hardcoded workload, same rule as the other engine legs
            raise SystemExit(
                "UNIONML_TPU_BENCH_PRESET=serve_rollout takes no CLI "
                f"flags or KV/PREFIX env legs (got {sys.argv[1:]}); its "
                "workload is hardcoded in rollout_leg"
            )
        rollout_leg()
    elif os.environ.get("UNIONML_TPU_BENCH_PRESET") == "serve_autoscale":
        if len(sys.argv) > 1 or os.environ.get("UNIONML_TPU_BENCH_KV") or (
            os.environ.get("UNIONML_TPU_BENCH_PREFIX")
        ):
            # hardcoded workload, same rule as the other engine legs
            raise SystemExit(
                "UNIONML_TPU_BENCH_PRESET=serve_autoscale takes no CLI "
                f"flags or KV/PREFIX env legs (got {sys.argv[1:]}); its "
                "workload is hardcoded in autoscale_leg"
            )
        autoscale_leg()
    elif os.environ.get("UNIONML_TPU_BENCH_PRESET") == "serve_router":
        if len(sys.argv) > 1 or os.environ.get("UNIONML_TPU_BENCH_KV") or (
            os.environ.get("UNIONML_TPU_BENCH_PREFIX")
        ):
            # hardcoded workload, same rule as the other engine legs
            raise SystemExit(
                "UNIONML_TPU_BENCH_PRESET=serve_router takes no CLI "
                f"flags or KV/PREFIX env legs (got {sys.argv[1:]}); its "
                "workload is hardcoded in router_leg"
            )
        router_leg()
    elif os.environ.get("UNIONML_TPU_BENCH_PRESET") == "serve_preempt":
        if len(sys.argv) > 1 or os.environ.get("UNIONML_TPU_BENCH_KV") or (
            os.environ.get("UNIONML_TPU_BENCH_PREFIX")
        ):
            # hardcoded workload, same rule as the other engine legs
            raise SystemExit(
                "UNIONML_TPU_BENCH_PRESET=serve_preempt takes no CLI "
                f"flags or KV/PREFIX env legs (got {sys.argv[1:]}); its "
                "workload is hardcoded in preempt_leg"
            )
        preempt_leg()
    elif os.environ.get("UNIONML_TPU_BENCH_PRESET") == "serve_usage":
        if len(sys.argv) > 1 or os.environ.get("UNIONML_TPU_BENCH_KV") or (
            os.environ.get("UNIONML_TPU_BENCH_PREFIX")
        ):
            # hardcoded workload, same rule as the other engine legs
            raise SystemExit(
                "UNIONML_TPU_BENCH_PRESET=serve_usage takes no CLI "
                f"flags or KV/PREFIX env legs (got {sys.argv[1:]}); its "
                "workload is hardcoded in usage_leg"
            )
        usage_leg()
    elif os.environ.get("UNIONML_TPU_BENCH_PRESET") == "serve_overload":
        if len(sys.argv) > 1 or os.environ.get("UNIONML_TPU_BENCH_KV") or (
            os.environ.get("UNIONML_TPU_BENCH_PREFIX")
        ):
            # hardcoded workload, same rule as serve_prefix_cache below
            raise SystemExit(
                "UNIONML_TPU_BENCH_PRESET=serve_overload takes no CLI "
                f"flags or KV/PREFIX env legs (got {sys.argv[1:]}); its "
                "workload is hardcoded in overload_leg"
            )
        overload_leg()
    elif os.environ.get("UNIONML_TPU_BENCH_PRESET") == "serve_prefix_cache":
        if len(sys.argv) > 1 or os.environ.get("UNIONML_TPU_BENCH_KV") or (
            os.environ.get("UNIONML_TPU_BENCH_PREFIX")
        ):
            # this leg never parses argv and replaces the env-triggered
            # legs — accepting either here would record its hardcoded
            # workload under the wrong labels
            raise SystemExit(
                "UNIONML_TPU_BENCH_PRESET=serve_prefix_cache takes no CLI "
                f"flags or KV/PREFIX env legs (got {sys.argv[1:]}); its "
                "workload is hardcoded in prefix_cache_engine_leg"
            )
        prefix_cache_engine_leg()
    elif os.environ.get("UNIONML_TPU_BENCH_KV") or os.environ.get(
        "UNIONML_TPU_BENCH_PREFIX"
    ):
        if len(sys.argv) > 1:
            # these legs never parse argv — accepting flags here would
            # record hardcoded-config numbers under the flags' labels
            raise SystemExit(
                "UNIONML_TPU_BENCH_KV/UNIONML_TPU_BENCH_PREFIX legs take "
                f"no CLI flags (got {sys.argv[1:]}); their configs are "
                "hardcoded in kv_cache_legs/prefix_cache_legs"
            )
        if os.environ.get("UNIONML_TPU_BENCH_KV"):
            kv_cache_legs()
        else:
            prefix_cache_legs()
    else:
        main()
