"""Serving-latency benchmark: Llama generation p50/p95 (BASELINE.md).

Reproduces the BASELINE.md serving rows: jitted prefill + scan decode
via :func:`unionml_tpu.models.make_generator` on a ~1.5B-param Llama-3
geometry (the largest that fits one v5e chip in bf16; the 8B config
needs the tensor-parallel path). Prints one JSON line per
(quantized, batch) combination.

Usage::

    python benchmarks/serve_latency.py [--batches 1 8] [--trials 20]
    UNIONML_TPU_BENCH_PRESET=tiny python benchmarks/serve_latency.py  # CPU smoke
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def serving_config(preset: str):
    from unionml_tpu.models import LlamaConfig

    if preset == "tiny":
        return LlamaConfig.tiny(vocab_size=256)
    if preset == "serve_moe":
        # ~1.1B-total-param 8-expert top-2 MoE (~0.4B active per token)
        return LlamaConfig(
            vocab_size=128_256, hidden_dim=1024, num_layers=12, num_heads=16,
            num_kv_heads=8, mlp_dim=2816, max_len=2048,
            num_experts=8, num_selected=2,
        )
    # ~1.5B params: Llama-3 geometry scaled to one v5e chip (bf16 ~3 GB)
    return LlamaConfig(
        vocab_size=128_256, hidden_dim=2048, num_layers=20, num_heads=16,
        num_kv_heads=8, mlp_dim=5632, max_len=2048,
    )


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batches", type=int, nargs="+", default=[1, 8])
    parser.add_argument("--trials", type=int, default=20)
    parser.add_argument("--prompt-len", type=int, default=64)
    parser.add_argument("--new-tokens", type=int, default=32)
    args = parser.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.models import (
        LLAMA_QUANT_PATTERNS,
        LlamaConfig,
        Llama,
        make_generator,
        quantize_params,
        serving_params,
    )

    backend = jax.default_backend()
    preset = os.environ.get(
        "UNIONML_TPU_BENCH_PRESET", "tiny" if backend == "cpu" else "serve_1p5b"
    )
    if preset == "tiny":
        args.trials = min(args.trials, 3)
    cfg = serving_config(preset)
    rng = np.random.default_rng(0)

    module = Llama(cfg)
    tokens0 = jnp.zeros((1, 8), jnp.int32)
    fp_params = jax.jit(module.init)(jax.random.PRNGKey(0), tokens0)["params"]
    # serving residency: one-time bf16 cast (decode re-reads weights per token)
    params = serving_params(fp_params)

    for quantized in (False, True):
        if quantized:
            qcfg = LlamaConfig(**{**cfg.__dict__, "quantized": True})
            qmodule = Llama(qcfg)
            # quantize from the fp32 masters (the production path), not the
            # bf16 serving copy: scales from bf16 weights double-round
            qparams = quantize_params(fp_params, LLAMA_QUANT_PATTERNS)
            run_module, run_params = qmodule, qparams
        else:
            run_module, run_params = module, params
        # cache sized to the request (make_lm_predictor does this per bucket)
        generate = make_generator(
            run_module, max_new_tokens=args.new_tokens,
            max_len=args.prompt_len + args.new_tokens,
        )
        for batch in args.batches:
            prompt = jnp.asarray(
                rng.integers(1, cfg.vocab_size, size=(batch, args.prompt_len)),
                jnp.int32,
            )
            # warmup/compile
            out = generate(run_params, prompt)
            _ = np.asarray(out)
            lat = []
            for _ in range(args.trials):
                t0 = time.perf_counter()
                out = generate(run_params, prompt)
                _ = np.asarray(out)  # host readback = end of request
                lat.append((time.perf_counter() - t0) * 1e3)
            lat.sort()
            p50 = lat[len(lat) // 2]
            p95 = lat[max(0, math.ceil(0.95 * len(lat)) - 1)]  # nearest-rank
            toks = batch * args.new_tokens / (p50 / 1e3)
            print(json.dumps({
                "metric": f"{preset}_generate_p50_ms",
                "quantized": quantized,
                "batch": batch,
                "prompt_len": args.prompt_len,
                "new_tokens": args.new_tokens,
                "value": round(p50, 1),
                "p95_ms": round(p95, 1),
                "tokens_per_sec": round(toks, 1),
                "unit": "ms",
            }))


if __name__ == "__main__":
    main()
