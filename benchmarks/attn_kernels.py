"""Attention-kernel selection benchmark (BASELINE.md kernel table).

Times each attention implementation (fwd+bwd, one jit program over a
12-layer chain) at the two regimes that drive the `attn_impl` defaults:

- short-seq ViT/BERT shape (64 x 197 x 12 x 64, non-causal) — where the
  one-program-per-batch `fused` kernel wins;
- long-seq LLM shape (4 x 4096 x 16 x 128, causal) — where the
  VMEM-tiled `flash` kernel wins.

Prints one JSON line per (regime, impl). On CPU backends Pallas kernels
run in interpret mode — use UNIONML_TPU_BENCH_PRESET=tiny for a smoke
run there.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.ops.attention import attention

    tiny = os.environ.get("UNIONML_TPU_BENCH_PRESET") == "tiny" or (
        jax.default_backend() == "cpu"
    )
    regimes = {
        "short_seq": dict(shape=(8, 64, 4, 16) if tiny else (64, 197, 12, 64),
                          causal=False, impls=("xla", "blockwise", "fused")),
        "long_seq": dict(shape=(1, 256, 4, 32) if tiny else (4, 4096, 16, 128),
                         causal=True, impls=("xla", "blockwise", "flash"),
                         layers=1),
    }
    steps, warmup = (3, 1) if tiny else (30, 5)

    for regime, spec in regimes.items():
        # chaining 12 layers of full 4096^2 score tensors through one bwd
        # program crashes the compiler; the long regime times one layer
        layers = spec.get("layers", 2 if tiny else 12)
        b, s, h, d = spec["shape"]
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (b, s, h, d), jnp.bfloat16)
        k = jax.random.normal(ks[1], (b, s, h, d), jnp.bfloat16)
        v = jax.random.normal(ks[2], (b, s, h, d), jnp.bfloat16)

        for impl in spec["impls"]:
            def loss(q, k, v, _impl=impl):
                x = q
                for _ in range(layers):
                    x = attention(x, k, v, impl=_impl, causal=spec["causal"])
                return jnp.sum(x.astype(jnp.float32) ** 2)

            grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            try:
                for _ in range(warmup):
                    out = grad(q, k, v)
                _ = float(np.asarray(out[0]).ravel()[0])
            except Exception as e:
                print(json.dumps({
                    "metric": f"attn_{regime}_{impl}_ms", "value": None,
                    "error": str(e)[:120],
                }))
                continue
            t0 = time.perf_counter()
            for _ in range(steps):
                out = grad(q, k, v)
            _ = float(np.asarray(out[0]).ravel()[0])
            ms = (time.perf_counter() - t0) / steps * 1e3
            print(json.dumps({
                "metric": f"attn_{regime}_{impl}_ms",
                "shape": [b, s, h, d],
                "layers": layers,
                "value": round(ms, 2),
                "unit": "ms (fwd+bwd)",
            }))


if __name__ == "__main__":
    main()
