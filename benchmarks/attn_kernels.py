"""Attention-kernel selection benchmark (BASELINE.md kernel table).

Times each attention implementation (fwd+bwd, one jit program over a
12-layer chain) at the two regimes that drive the `attn_impl` defaults:

- short-seq ViT/BERT shape (64 x 197 x 12 x 64, non-causal) — where the
  one-program-per-batch `fused` kernel wins;
- long-seq LLM shape (4 x 4096 x 16 x 128, causal) — where the
  VMEM-tiled `flash` kernel wins.

Plus the PAGED DECODE leg (docs/performance.md "Paged KV attention"):
one decode step against a block-paged KV pool at block sizes 16/32/64
vs the contiguous cached-attention baseline — per-step latency and the
KV bytes each layout moves, so the engine's `kv_block_size` choice is
data-driven (smaller blocks waste fewer tail rows, larger blocks cut
per-block gather overhead).

Prints one JSON line per (regime, impl). On CPU backends Pallas kernels
run in interpret mode — use UNIONML_TPU_BENCH_PRESET=tiny for a smoke
run there.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.ops.attention import attention

    tiny = os.environ.get("UNIONML_TPU_BENCH_PRESET") == "tiny" or (
        jax.default_backend() == "cpu"
    )
    regimes = {
        "short_seq": dict(shape=(8, 64, 4, 16) if tiny else (64, 197, 12, 64),
                          causal=False, impls=("xla", "blockwise", "fused")),
        "long_seq": dict(shape=(1, 256, 4, 32) if tiny else (4, 4096, 16, 128),
                         causal=True, impls=("xla", "blockwise", "flash"),
                         layers=1),
    }
    steps, warmup = (3, 1) if tiny else (30, 5)

    for regime, spec in regimes.items():
        # chaining 12 layers of full 4096^2 score tensors through one bwd
        # program crashes the compiler; the long regime times one layer
        layers = spec.get("layers", 2 if tiny else 12)
        b, s, h, d = spec["shape"]
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(ks[0], (b, s, h, d), jnp.bfloat16)
        k = jax.random.normal(ks[1], (b, s, h, d), jnp.bfloat16)
        v = jax.random.normal(ks[2], (b, s, h, d), jnp.bfloat16)

        for impl in spec["impls"]:
            def loss(q, k, v, _impl=impl):
                x = q
                for _ in range(layers):
                    x = attention(x, k, v, impl=_impl, causal=spec["causal"])
                return jnp.sum(x.astype(jnp.float32) ** 2)

            grad = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
            try:
                for _ in range(warmup):
                    out = grad(q, k, v)
                _ = float(np.asarray(out[0]).ravel()[0])
            except Exception as e:
                print(json.dumps({
                    "metric": f"attn_{regime}_{impl}_ms", "value": None,
                    "error": str(e)[:120],
                }))
                continue
            t0 = time.perf_counter()
            for _ in range(steps):
                out = grad(q, k, v)
            _ = float(np.asarray(out[0]).ravel()[0])
            ms = (time.perf_counter() - t0) / steps * 1e3
            print(json.dumps({
                "metric": f"attn_{regime}_{impl}_ms",
                "shape": [b, s, h, d],
                "layers": layers,
                "value": round(ms, 2),
                "unit": "ms (fwd+bwd)",
            }))

    paged_decode_leg(tiny, steps, warmup)


def paged_decode_leg(tiny: bool, steps: int, warmup: int) -> None:
    """Paged-vs-contiguous decode microbench at block sizes 16/32/64.

    One decode step: [slots] single-token queries against [slots]
    resident sequences at mixed fill depths (a long-tail mix — half the
    slots shallow, half deep, the workload paging exists for). The
    contiguous baseline reads the full [slots, max_len] cache; the
    paged kernel gathers only each slot's covered blocks. ``kv_bytes``
    is the per-step KV traffic each layout issues — the HBM-bound
    quantity that sets decode throughput."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.ops.attention import cached_attention
    from unionml_tpu.ops.paged_attention import paged_attention

    if tiny:
        slots, kvh, heads, d, max_len = 4, 2, 4, 16, 128
        block_sizes = (16, 32, 64)
    else:
        slots, kvh, heads, d, max_len = 8, 8, 32, 128, 4096
        block_sizes = (16, 32, 64)
    # long-tail fills: half the slots at 1/8 depth, half near max
    fills = np.where(
        np.arange(slots) % 2 == 0, max_len // 8, max_len - max_len // 8
    ).astype(np.int32)
    q = jax.random.normal(
        jax.random.PRNGKey(1), (slots, heads, d), jnp.bfloat16
    )
    itemsize = 2  # bf16

    # ---- contiguous baseline: full [slots, max_len] cache read ----
    ck = jax.random.normal(
        jax.random.PRNGKey(2), (slots, max_len, kvh, d), jnp.bfloat16
    )
    cv = jax.random.normal(
        jax.random.PRNGKey(3), (slots, max_len, kvh, d), jnp.bfloat16
    )
    kv_pos = jnp.arange(max_len)[None, :]
    bias = jnp.where(
        (kv_pos[None] <= (jnp.asarray(fills) - 1)[:, None, None]),
        0.0, -1e30,
    )[:, None]

    def contiguous_step(q, ck, cv, bias):
        return cached_attention(q[:, None], ck, cv, bias=bias)[:, 0]

    step = jax.jit(contiguous_step)
    out = step(q, ck, cv, bias)
    out.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(steps):
        out = step(q, ck, cv, bias)
    out.block_until_ready()
    ms = (time.perf_counter() - t0) / steps * 1e3
    contig_bytes = 2 * slots * max_len * kvh * d * itemsize
    print(json.dumps({
        "metric": "attn_paged_decode_contiguous_ms",
        "slots": slots, "max_len": max_len, "fills": fills.tolist(),
        "kv_bytes": contig_bytes,
        "value": round(ms, 3), "unit": "ms/step",
    }))

    # ---- paged: gather only the covered blocks, per block size ----
    impl = "reference" if jax.default_backend() == "cpu" else "pallas"
    for bs in block_sizes:
        w = max_len // bs
        covered = [int(-(-f // bs)) for f in fills]
        n_pool = 1 + sum(covered)
        pool_k = jax.random.normal(
            jax.random.PRNGKey(4), (n_pool, bs, kvh, d), jnp.bfloat16
        )
        pool_v = jax.random.normal(
            jax.random.PRNGKey(5), (n_pool, bs, kvh, d), jnp.bfloat16
        )
        table = np.zeros((slots, w), np.int32)
        nid = 1
        for s_i, c in enumerate(covered):
            for j in range(c):
                table[s_i, j] = nid
                nid += 1
        table = jnp.asarray(table)
        lengths = jnp.asarray(fills)

        pstep = jax.jit(
            lambda q, k, v, t, ln: paged_attention(q, k, v, t, ln, impl=impl)
        )
        out = pstep(q, pool_k, pool_v, table, lengths)
        out.block_until_ready()
        t0 = time.perf_counter()
        for _ in range(steps):
            out = pstep(q, pool_k, pool_v, table, lengths)
        out.block_until_ready()
        ms = (time.perf_counter() - t0) / steps * 1e3
        paged_bytes = 2 * sum(covered) * bs * kvh * d * itemsize
        print(json.dumps({
            "metric": f"attn_paged_decode_bs{bs}_ms",
            "slots": slots, "max_len": max_len, "impl": impl,
            "kv_bytes": paged_bytes,
            "kv_bytes_vs_contiguous": round(paged_bytes / contig_bytes, 3),
            "value": round(ms, 3), "unit": "ms/step",
        }))


if __name__ == "__main__":
    main()
