"""Dependency-free lint: the high-value correctness subset, stdlib-only.

The reference gates on full flake8/mypy; this image ships neither, so
this AST-based checker enforces the subset that catches real bugs and
runs anywhere (CI executes it alongside flake8 — flake8 remains the
richer gate where installed):

- F401-equivalent: unused imports (module scope, `__init__.py` exempt —
  package surfaces re-export),
- mutable default arguments,
- bare ``except:``,
- comparisons to ``None``/``True``/``False`` with ``==``/``!=``,
- f-strings without placeholders,
- tabs in indentation and trailing whitespace,
- lines over 110 columns (the codebase targets ~100; 110 is the hard
  stop so URLs/tables don't nag),
- bare ``time.time()`` in the serving layer and the execution engine
  (:data:`WALL_CLOCK_BANNED`): durations there MUST use
  ``time.monotonic()``/``time.perf_counter()`` — wall clock steps under
  NTP slew and breaks deadline/latency accounting. (``time.time()`` is
  fine elsewhere, e.g. epoch timestamps in logs.)
- direct ``cache[...]`` subscripts in ``unionml_tpu/serving/`` outside
  the block allocator module (:data:`CACHE_INDEX_BANNED` /
  :data:`CACHE_INDEX_EXEMPT`): since the paged-KV refactor
  (docs/performance.md), device KV rows are addressed through block
  tables — contiguous-row indexing of a cache object in serving code
  bypasses the allocator and silently breaks the paged layout. Route
  through the block-table API (``kv_pool.py`` + the engine's
  scatter/extract programs) instead.
- label-cardinality guard (repo-wide, when the default paths are
  linted): any ``unionml_*`` metric registered under ``unionml_tpu/``
  whose label schema contains a **request-derived** label name
  (:data:`REQUEST_DERIVED_LABELS` — tenant/rid/request ids) must live
  in the usage ledger module (:data:`REQUEST_LABEL_EXEMPT`), whose
  top-K rollup bounds the label's value set. Anywhere else, a
  request-derived label means unbounded series cardinality the moment
  a client controls the value — route the increment through
  ``UsageLedger.label_for`` instead (docs/observability.md "Usage
  metering & cost attribution").
- metrics-doc drift (repo-wide, when the default paths are linted):
  every ``unionml_*`` metric registered under ``unionml_tpu/`` must be
  documented in ``docs/observability.md``, and every full metric name
  the doc mentions must exist in code — the by-hand doc table
  accumulated drift across PRs 1–4; this closes the loop both ways.

Usage: ``python scripts/lint_basics.py [paths...]`` (default: the
package, tests, benchmarks, scripts). Exits non-zero on findings.
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parents[1]
DEFAULT_PATHS = ["unionml_tpu", "tests", "benchmarks", "scripts", "bench.py",
                 "__graft_entry__.py"]
MAX_LINE = 110

# repo-relative prefixes where time.time() is banned (monotonic-clock
# territory: queue deadlines, latency splits, drain timers — and, since
# the goodput layer, every trainer path whose durations feed badput
# buckets: wall clock stepping under NTP would mis-attribute seconds).
# The checkpoint/ prefix covers async_writer.py: its save_ms/commit_ms
# split IS the checkpoint badput attribution, so a wall-clock duration
# there would corrupt the caller-stall vs background-commit story.
# The serving/ prefix covers router.py: the fleet router's ejection
# cooldowns, hedge delays, and backoff timers are exactly the durations
# an NTP step would corrupt into spurious ejections or storms.
# The serving/ prefix also covers scheduler.py: the preemptive
# scheduler's resume-wait spans and KV hold windows feed latency
# attribution and per-tenant billing — wall-clock stepping there would
# corrupt preemption accounting and the deficit queues' fairness.
WALL_CLOCK_BANNED = (
    "unionml_tpu/serving/",
    "unionml_tpu/execution.py",
    "unionml_tpu/goodput.py",
    "unionml_tpu/elastic.py",
    "unionml_tpu/data/pipeline.py",
    "unionml_tpu/checkpoint/",
)

# where direct `cache[...]` / `<expr>.cache[...]` subscripts are banned:
# serving-layer device KV goes through the block-table API so the paged
# and contiguous layouts cannot silently diverge. The allocator module
# itself is the one legitimate home for raw block addressing.
CACHE_INDEX_BANNED = ("unionml_tpu/serving/",)
CACHE_INDEX_EXEMPT = ("unionml_tpu/serving/kv_pool.py",)


class Checker(ast.NodeVisitor):
    def __init__(self, path: Path, src: str, ban_wall_clock: bool = False,
                 ban_cache_index: bool = False):
        self.path = path
        self.src = src
        self.ban_wall_clock = ban_wall_clock
        self.ban_cache_index = ban_cache_index
        self.problems: list = []
        self.imports: dict = {}       # name -> (lineno, spelled)
        self.used: set = set()

    def problem(self, lineno: int, msg: str):
        self.problems.append(f"{self.path}:{lineno}: {msg}")

    # -- imports ------------------------------------------------------- #

    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            name = alias.asname or alias.name.split(".")[0]
            self.imports[name] = (node.lineno, alias.name)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module == "__future__":
            return  # compiler directive, never "used"
        for alias in node.names:
            if alias.name == "*":
                continue
            name = alias.asname or alias.name
            self.imports[name] = (node.lineno, alias.name)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name):
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        root = node
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name):
            self.used.add(root.id)
        self.generic_visit(node)

    # -- defaults / except / comparisons / f-strings ------------------- #

    def _check_defaults(self, node):
        for default in list(node.args.defaults) + list(node.args.kw_defaults):
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                self.problem(
                    default.lineno,
                    f"mutable default argument in {node.name}()",
                )

    def visit_FunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node):
        if node.type is None:
            self.problem(node.lineno, "bare except: (catch a class)")
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare):
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)) and isinstance(
                comp, ast.Constant
            ) and (comp.value is None or comp.value is True or comp.value is False):
                self.problem(
                    node.lineno,
                    f"comparison to {comp.value!r} with ==/!= (use is/is not)",
                )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        if (
            self.ban_wall_clock
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "time"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "time"
        ):
            self.problem(
                node.lineno,
                "time.time() in serving/execution code — use "
                "time.monotonic()/time.perf_counter() for durations "
                "(wall clock steps under NTP)",
            )
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        if self.ban_cache_index:
            target = node.value
            name = (
                target.id if isinstance(target, ast.Name)
                else target.attr if isinstance(target, ast.Attribute)
                else None
            )
            if name == "cache":
                self.problem(
                    node.lineno,
                    "direct cache[...] indexing in serving code — device "
                    "KV rows are block-paged; go through the block-table "
                    "API (serving/kv_pool.py + the engine's "
                    "scatter/extract programs)",
                )
        self.generic_visit(node)

    def visit_JoinedStr(self, node: ast.JoinedStr):
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.problem(node.lineno, "f-string without placeholders")
        self.generic_visit(node)

    def visit_FormattedValue(self, node: ast.FormattedValue):
        # do NOT descend into format_spec: "{x:.2e}" carries a nested
        # placeholder-free JoinedStr that is not a user f-string
        self.visit(node.value)

    # -- finish -------------------------------------------------------- #

    def report_unused_imports(self, tree: ast.Module):
        if self.path.name == "__init__.py":
            return
        # names exported via __all__ or re-exported strings count as used
        exported = set()
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and any(
                    isinstance(t, ast.Name) and t.id == "__all__"
                    for t in node.targets
                )
                and isinstance(node.value, (ast.List, ast.Tuple))
            ):
                exported |= {
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                }
        for name, (lineno, spelled) in self.imports.items():
            if name in self.used or name in exported:
                continue
            # "import x.y" spells a submodule import for side effects
            if "." in spelled and name == spelled.split(".")[0]:
                continue
            self.problem(lineno, f"unused import: {spelled}")


def check_file(path: Path) -> list:
    src = path.read_text(encoding="utf-8")
    try:
        tree = ast.parse(src, filename=str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    try:
        rel = path.resolve().relative_to(ROOT).as_posix()
    except ValueError:
        rel = path.as_posix()
    ban_wall_clock = any(
        rel == p or rel.startswith(p) for p in WALL_CLOCK_BANNED
    )
    ban_cache_index = any(
        rel == p or rel.startswith(p) for p in CACHE_INDEX_BANNED
    ) and rel not in CACHE_INDEX_EXEMPT
    checker = Checker(
        path, src, ban_wall_clock=ban_wall_clock,
        ban_cache_index=ban_cache_index,
    )
    checker.visit(tree)
    checker.report_unused_imports(tree)
    for i, line in enumerate(src.splitlines(), 1):
        if "\t" in line[: len(line) - len(line.lstrip())]:
            checker.problem(i, "tab in indentation")
        if line != line.rstrip():
            checker.problem(i, "trailing whitespace")
        if len(line) > MAX_LINE:
            checker.problem(i, f"line too long ({len(line)} > {MAX_LINE})")
    return checker.problems


METRICS_DOC = "docs/observability.md"
# a registration call looks like registry.counter("name", ...) /
# .gauge(...) / .histogram(...) — or the engine/batcher's local helper
# shorthands counter("name", ...) / hist("name", ...); the first
# positional string is the name either way
_METRIC_FACTORIES = ("counter", "gauge", "histogram", "hist")
# doc tokens that LOOK like metric names: the unionml_ prefix plus at
# least two more underscore-separated words (filters out module-ish
# mentions like `unionml_tpu.telemetry` → token "unionml_tpu" — while
# real metric names, `unionml_tpu_build_info` included, always qualify)
_DOC_METRIC_RE = re.compile(r"\bunionml(?:_[a-z0-9]+){2,}\b")
# histogram/counter exposition suffixes a doc may legitimately mention
_SERIES_SUFFIXES = ("_bucket", "_sum", "_count")


# request-derived label names: a client-controlled value minted into a
# label is unbounded cardinality — only the usage ledger's bounded
# top-K rollup may own such labels
REQUEST_DERIVED_LABELS = (
    "tenant", "rid", "request_id", "user", "user_id", "client",
    "client_id",
)
REQUEST_LABEL_EXEMPT = ("unionml_tpu/serving/usage.py",)


# the CLOSED trace-span-name vocabulary (the autoscaler's
# DECISION_REASONS pattern applied to spans): every literal span name
# recorded into the TraceRecorder must come from this set, and every
# name here must be documented in docs/observability.md — so the
# stitched fleet timeline's vocabulary stays a documented enum that
# OTLP consumers (grouping, alerting on span names) can rely on.
# Names recorded from variables (the goodput tracker's phase names are
# the BADPUT_CAUSES vocabulary, enforced at runtime) are not checkable
# statically and are skipped.
TRACE_SPAN_NAMES = (
    # engine request lifecycle
    "queue", "prefill", "harvest", "recover",
    # micro-batcher
    "predict",
    # fleet router decision machinery (docs/observability.md
    # "Fleet observability")
    "pick", "attempt", "backoff", "hedge-lane",
    # disaggregated two-leg dispatch (docs/serving.md "Disaggregated
    # serving"): the prefill leg, the KV handoff between pools, and
    # the decode leg — all under one routing rid, joining the engine
    # prefill/prefix-splice families each leg records on its replica
    "prefill-leg", "handoff", "decode-leg",
    # rollout shadow dispatch (docs/robustness.md "Rollouts &
    # rollback"): the canary-side duplicate of a live request, on its
    # own timeline under the live request's trace id so
    # /debug/trace?rid=<live> stitches both paths
    "shadow",
)
# indexed span families (f-strings with a bounded constant prefix) and
# the transport server span (f"http {path}" — path is route-bounded)
TRACE_SPAN_PREFIXES = (
    "decode-chunk[", "prefill-chunk[", "prefix-splice[",
    "resume-wait[", "preempt[", "http ",
)
TRACE_SPAN_EXEMPT = (
    "unionml_tpu/telemetry.py",   # the recorder mechanism itself
)


def _span_name_literal(node: ast.Call):
    """The span-name argument of a ``record_span`` call when it is
    statically checkable: ``(kind, value)`` where kind is "const" for
    a string literal, "prefix" for an f-string's leading constant
    part, or None for a variable (skipped)."""
    if len(node.args) < 2:
        return None, None
    name_arg = node.args[1]
    if isinstance(name_arg, ast.Constant) and isinstance(
        name_arg.value, str
    ):
        return "const", name_arg.value
    if isinstance(name_arg, ast.JoinedStr):
        prefix = ""
        for value in name_arg.values:
            if isinstance(value, ast.Constant) and isinstance(
                value.value, str
            ):
                prefix += value.value
            else:
                break
        return "prefix", prefix
    return None, None


def check_span_names(package_root: Path) -> list:
    """Every literal span name at a ``record_span`` call site must be
    in :data:`TRACE_SPAN_NAMES` (constants) or open with a
    :data:`TRACE_SPAN_PREFIXES` family (f-strings), and the whole
    vocabulary must be documented in docs/observability.md — the
    span-name twin of the metrics-doc drift check."""
    problems = []
    for path in sorted(package_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        try:
            rel = path.resolve().relative_to(ROOT).as_posix()
        except ValueError:
            rel = path.as_posix()
        if rel in TRACE_SPAN_EXEMPT:
            continue
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"))
        except SyntaxError:
            continue  # reported by the per-file checker
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "record_span"
            ):
                continue
            kind, name = _span_name_literal(node)
            if kind is None:
                continue  # variable name: runtime-enforced vocabulary
            if kind == "const" and name in TRACE_SPAN_NAMES:
                continue
            if kind == "prefix" and name and any(
                name.startswith(p) for p in TRACE_SPAN_PREFIXES
            ):
                # the f-string's constant prefix must COVER a family
                # prefix — the reverse test would let f"p{x}" ride in
                # on "preempt[" and silently widen the closed set
                continue
            problems.append(
                f"{path}:{node.lineno}: span name {name!r} is outside "
                "the closed TRACE_SPAN_NAMES/TRACE_SPAN_PREFIXES set "
                "(scripts/lint_basics.py) — span names are a "
                "documented enum; add it there AND to "
                f"{METRICS_DOC}, or reuse an existing name"
            )
    doc_path = ROOT / METRICS_DOC
    if doc_path.exists():
        doc_text = doc_path.read_text(encoding="utf-8")
        for name in TRACE_SPAN_NAMES + tuple(
            p.rstrip("[ ") for p in TRACE_SPAN_PREFIXES
        ):
            if name not in doc_text:
                problems.append(
                    f"{METRICS_DOC}: span name {name!r} from the "
                    "TRACE_SPAN_NAMES enum is not documented"
                )
    return problems


ROLLOUT_MODULE = "unionml_tpu/serving/rollout.py"
ROLLOUT_DOC = "docs/robustness.md"
# the doc's decision table is fenced by these markers so the reverse
# direction of the drift check has a bounded region to scan (free-text
# prose may mention a reason informally without being "the table")
_ROLLOUT_DOC_BEGIN = "<!-- ROLLOUT_REASONS:begin -->"
_ROLLOUT_DOC_END = "<!-- ROLLOUT_REASONS:end -->"
_BACKTICK_TOKEN_RE = re.compile(r"`([a-z0-9_]+)`")


def _module_tuple_literal(tree: ast.Module, name: str):
    """The string elements of a module-level ``NAME = (...)`` tuple
    assignment, or None when absent/not-a-literal."""
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets
            )
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            return tuple(
                e.value for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
    return None


def check_rollout_reasons(root: Path) -> list:
    """Two-way drift check between the rollout controller's closed
    decision vocabulary (``ROLLOUT_DECISIONS``/``ROLLOUT_REASONS`` in
    serving/rollout.py) and the decision table in docs/robustness.md
    "Rollouts & rollback" — the DECISION_REASONS/span-name pattern
    applied to the rollout state machine, so an operator paging
    through ``unionml_rollout_decisions_total{decision,reason}`` can
    trust every label value has a documented row."""
    module_path = root / ROLLOUT_MODULE
    doc_path = root / ROLLOUT_DOC
    if not module_path.exists():
        return [f"{ROLLOUT_MODULE}: missing (rollout drift check needs it)"]
    try:
        tree = ast.parse(module_path.read_text(encoding="utf-8"))
    except SyntaxError:
        return []  # reported by the per-file checker
    reasons = _module_tuple_literal(tree, "ROLLOUT_REASONS")
    decisions = _module_tuple_literal(tree, "ROLLOUT_DECISIONS")
    problems = []
    if reasons is None or decisions is None:
        return [
            f"{ROLLOUT_MODULE}: ROLLOUT_REASONS/ROLLOUT_DECISIONS must "
            "be module-level literal tuples (the closed vocabulary the "
            "doc-drift check parses)"
        ]
    if not doc_path.exists():
        return [f"{ROLLOUT_DOC}: missing (rollout drift check needs it)"]
    doc_text = doc_path.read_text(encoding="utf-8")
    for value in decisions + reasons:
        if f"`{value}`" not in doc_text:
            problems.append(
                f"{ROLLOUT_MODULE}: rollout vocabulary value "
                f"{value!r} is not documented in {ROLLOUT_DOC}"
            )
    begin = doc_text.find(_ROLLOUT_DOC_BEGIN)
    end = doc_text.find(_ROLLOUT_DOC_END)
    if begin < 0 or end < 0 or end < begin:
        problems.append(
            f"{ROLLOUT_DOC}: decision table must be fenced by "
            f"{_ROLLOUT_DOC_BEGIN} / {_ROLLOUT_DOC_END} markers (the "
            "reverse drift direction scans that region)"
        )
        return problems
    known = set(decisions) | set(reasons)
    offset = doc_text[:begin].count("\n") + 1
    for lineno, line in enumerate(
        doc_text[begin:end].splitlines(), offset
    ):
        for token in _BACKTICK_TOKEN_RE.findall(line):
            if token not in known:
                problems.append(
                    f"{ROLLOUT_DOC}:{lineno}: decision-table token "
                    f"{token!r} is not in the ROLLOUT_DECISIONS/"
                    f"ROLLOUT_REASONS vocabulary ({ROLLOUT_MODULE})"
                )
    return problems


PERF_MODULE = "unionml_tpu/serving/perf.py"
PERF_DOC = "docs/observability.md"
_PERF_DOC_BEGIN = "<!-- PERF_REASONS:begin -->"
_PERF_DOC_END = "<!-- PERF_REASONS:end -->"


def check_perf_reasons(root: Path) -> list:
    """Two-way drift check between the serving perf watchdog's closed
    reasons vocabulary (``PERF_REGRESSION_REASONS`` in serving/perf.py)
    and the watchdog reasons table in docs/observability.md "Serving
    goodput & tail attribution" — the rollout-decision pattern applied
    to ``perf_regression`` flight events, so an operator filtering
    ``/debug/flight?kind=perf_regression`` can trust every ``reason``
    value has a documented row."""
    module_path = root / PERF_MODULE
    doc_path = root / PERF_DOC
    if not module_path.exists():
        return [f"{PERF_MODULE}: missing (perf-reasons drift check needs it)"]
    try:
        tree = ast.parse(module_path.read_text(encoding="utf-8"))
    except SyntaxError:
        return []  # reported by the per-file checker
    reasons = _module_tuple_literal(tree, "PERF_REGRESSION_REASONS")
    if reasons is None:
        return [
            f"{PERF_MODULE}: PERF_REGRESSION_REASONS must be a "
            "module-level literal tuple (the closed vocabulary the "
            "doc-drift check parses)"
        ]
    if not doc_path.exists():
        return [f"{PERF_DOC}: missing (perf-reasons drift check needs it)"]
    problems = []
    doc_text = doc_path.read_text(encoding="utf-8")
    for value in reasons:
        if f"`{value}`" not in doc_text:
            problems.append(
                f"{PERF_MODULE}: watchdog reason {value!r} is not "
                f"documented in {PERF_DOC}"
            )
    begin = doc_text.find(_PERF_DOC_BEGIN)
    end = doc_text.find(_PERF_DOC_END)
    if begin < 0 or end < 0 or end < begin:
        problems.append(
            f"{PERF_DOC}: watchdog reasons table must be fenced by "
            f"{_PERF_DOC_BEGIN} / {_PERF_DOC_END} markers (the reverse "
            "drift direction scans that region)"
        )
        return problems
    known = set(reasons)
    offset = doc_text[:begin].count("\n") + 1
    for lineno, line in enumerate(doc_text[begin:end].splitlines(), offset):
        for token in _BACKTICK_TOKEN_RE.findall(line):
            if token not in known:
                problems.append(
                    f"{PERF_DOC}:{lineno}: watchdog-reasons token "
                    f"{token!r} is not in the PERF_REGRESSION_REASONS "
                    f"vocabulary ({PERF_MODULE})"
                )
    return problems


# Closed flight-event vocabulary: every *literal* kind recorded via a
# ``*_flight_rec("kind", ...)`` / ``*flight*.record("kind", ...)`` call
# under unionml_tpu/ must be listed here AND in the fenced table in
# docs/observability.md — a postmortem filter (`/debug/flight?kind=`)
# and the fleet merge both key on these strings, so an undocumented or
# typo'd kind is an invisible event class. (Variable-kind pass-through
# sites — e.g. the rollout controller recording its decision enum — are
# covered by their own closed-set checks.)
FLIGHT_EVENT_KINDS = (
    # engine lifecycle
    "submit", "reject", "prefill", "decode", "finish", "drop",
    "promote", "preempt", "resume", "pool_pressure", "recovery",
    # micro-batcher
    "batch", "error",
    # fleet router / membership / dispatch
    "join", "leave", "rejoin", "drain", "eject", "probe", "route",
    "retry", "hedge",
    # autoscaler
    "scale_out", "scale_in", "scale_hold", "scale_reap",
    # disaggregated serving
    "handoff",
    # rollouts
    "rollout_shadow",
    # training goodput plane
    "train_compile", "step_time_anomaly", "step_time_regression",
    "straggler",
    # serving perf plane
    "perf_regression",
)
_FLIGHT_DOC_BEGIN = "<!-- FLIGHT_EVENT_KINDS:begin -->"
_FLIGHT_DOC_END = "<!-- FLIGHT_EVENT_KINDS:end -->"


def _flight_kind_literal(node: ast.Call):
    """The literal kind string of a flight-record call, or None when
    the call is not a flight record / the kind is not a literal."""
    func = node.func
    if not (isinstance(func, ast.Attribute) and node.args):
        return None
    if func.attr == "_flight_rec":
        pass
    elif func.attr == "record" and "flight" in ast.unparse(func.value):
        pass
    else:
        return None
    arg = node.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return None


def check_flight_event_kinds(root: Path) -> list:
    """Both directions of the flight-event vocabulary contract: every
    literal kind recorded under unionml_tpu/ must be in
    ``FLIGHT_EVENT_KINDS``, and every backticked token in the fenced
    docs/observability.md table must be a known kind."""
    problems = []
    for path in sorted((root / "unionml_tpu").rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"))
        except SyntaxError:
            continue  # reported by the per-file checker
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _flight_kind_literal(node)
            if kind is not None and kind not in FLIGHT_EVENT_KINDS:
                problems.append(
                    f"{path}:{node.lineno}: flight event kind {kind!r} "
                    "is not in FLIGHT_EVENT_KINDS (scripts/"
                    "lint_basics.py) — extend the closed vocabulary "
                    "and its docs/observability.md table"
                )
    doc_path = root / METRICS_DOC
    if not doc_path.exists():
        return problems + [
            f"{METRICS_DOC}: missing (flight-kind drift check needs it)"
        ]
    doc_text = doc_path.read_text(encoding="utf-8")
    begin = doc_text.find(_FLIGHT_DOC_BEGIN)
    end = doc_text.find(_FLIGHT_DOC_END)
    if begin < 0 or end < 0 or end < begin:
        problems.append(
            f"{METRICS_DOC}: flight-event kinds must be fenced by "
            f"{_FLIGHT_DOC_BEGIN} / {_FLIGHT_DOC_END} markers (the "
            "reverse drift direction scans that region)"
        )
        return problems
    region = doc_text[begin:end]
    offset = doc_text[:begin].count("\n") + 1
    for lineno, line in enumerate(region.splitlines(), offset):
        for token in _BACKTICK_TOKEN_RE.findall(line):
            if token not in FLIGHT_EVENT_KINDS:
                problems.append(
                    f"{METRICS_DOC}:{lineno}: flight-kind token "
                    f"{token!r} is not in FLIGHT_EVENT_KINDS "
                    "(scripts/lint_basics.py)"
                )
    for kind in FLIGHT_EVENT_KINDS:
        if f"`{kind}`" not in region:
            problems.append(
                f"{METRICS_DOC}: flight event kind {kind!r} is missing "
                "from the fenced FLIGHT_EVENT_KINDS table"
            )
    return problems


def _call_labelnames(node: ast.Call):
    """Constant label names of a metric registration call: the third
    positional arg or the ``labelnames`` kwarg, when it is a literal
    tuple/list of strings (the codebase's only registration idiom)."""
    label_arg = node.args[2] if len(node.args) >= 3 else None
    for kw in node.keywords:
        if kw.arg == "labelnames":
            label_arg = kw.value
    if not isinstance(label_arg, (ast.Tuple, ast.List)):
        return ()
    return tuple(
        e.value for e in label_arg.elts
        if isinstance(e, ast.Constant) and isinstance(e.value, str)
    )


def check_label_cardinality(package_root: Path) -> list:
    """Every ``unionml_*`` registration whose label schema contains a
    request-derived name must live in the ledger module — the single
    home of the bounded rollup that keeps such labels finite."""
    problems = []
    for path in sorted(package_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        try:
            rel = path.resolve().relative_to(ROOT).as_posix()
        except ValueError:
            rel = path.as_posix()
        if rel in REQUEST_LABEL_EXEMPT:
            continue
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"))
        except SyntaxError:
            continue  # reported by the per-file checker
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            func = node.func
            factory = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else None
            )
            if factory not in _METRIC_FACTORIES or not (
                isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("unionml_")
            ):
                continue
            bad = [
                label for label in _call_labelnames(node)
                if label in REQUEST_DERIVED_LABELS
            ]
            if bad:
                problems.append(
                    f"{path}:{node.lineno}: metric "
                    f"{node.args[0].value} takes request-derived "
                    f"label(s) {bad} outside the usage ledger — route "
                    "through UsageLedger's bounded top-K rollup "
                    "(unionml_tpu/serving/usage.py) so a client cannot "
                    "mint unbounded series"
                )
    return problems


def registered_metric_names(package_root: Path) -> dict:
    """``{metric_name: "file:line"}`` for every ``unionml_*`` metric
    registered under the package (AST walk: the first string argument
    of a ``.counter/.gauge/.histogram(...)`` call)."""
    names: dict = {}
    for path in sorted(package_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"))
        except SyntaxError:
            continue  # reported by the per-file checker
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            func = node.func
            factory = (
                func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else None
            )
            if factory not in _METRIC_FACTORIES or not (
                isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            name = node.args[0].value
            if name.startswith("unionml_"):
                names.setdefault(name, f"{path}:{node.args[0].lineno}")
    return names


def check_metrics_doc(root: Path) -> list:
    """Both directions of the metrics/doc contract: registered names
    must be documented; documented full names must be registered."""
    doc_path = root / METRICS_DOC
    if not doc_path.exists():
        return [f"{METRICS_DOC}: missing (metric drift check needs it)"]
    doc_text = doc_path.read_text(encoding="utf-8")
    registered = registered_metric_names(root / "unionml_tpu")
    problems = []
    for name, where in sorted(registered.items()):
        if name not in doc_text:
            problems.append(
                f"{where}: metric {name} is not documented in "
                f"{METRICS_DOC}"
            )
    known = set(registered)
    for name in known.copy():
        known.update(name + suffix for suffix in _SERIES_SUFFIXES)
    for lineno, line in enumerate(doc_text.splitlines(), 1):
        for token in _DOC_METRIC_RE.findall(line):
            if token not in known:
                problems.append(
                    f"{METRICS_DOC}:{lineno}: documented metric {token} "
                    "is not registered anywhere under unionml_tpu/"
                )
    return problems


def main(argv) -> int:
    paths = argv or DEFAULT_PATHS
    files: list = []
    for p in paths:
        path = (ROOT / p) if not Path(p).is_absolute() else Path(p)
        if path.is_dir():
            files.extend(sorted(path.rglob("*.py")))
        elif path.suffix == ".py" and path.exists():
            files.append(path)
        else:
            # a typo'd path must not green-light unlinted code
            print(f"lint_basics: path does not resolve: {p}")
            return 2
    problems: list = []
    for f in files:
        if "__pycache__" in f.parts:
            continue
        problems.extend(check_file(f))
    if paths is DEFAULT_PATHS or "unionml_tpu" in paths:
        # repo-wide contracts, meaningful only when the package is in
        # scope (a single-file lint must not fail on doc drift). The
        # default `make lint` target always lands here, so the
        # metrics↔docs drift check and the span-name enum run on
        # every lint, not just when someone remembers to ask.
        problems.extend(check_metrics_doc(ROOT))
        problems.extend(check_label_cardinality(ROOT / "unionml_tpu"))
        problems.extend(check_span_names(ROOT / "unionml_tpu"))
        problems.extend(check_rollout_reasons(ROOT))
        problems.extend(check_perf_reasons(ROOT))
        problems.extend(check_flight_event_kinds(ROOT))
    for p in problems:
        print(p)
    print(f"lint_basics: {len(files)} files, {len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
