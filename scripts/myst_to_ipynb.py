#!/usr/bin/env python
"""Convert MyST executable tutorials to Jupyter notebooks.

Reference analog: scripts/myst_to_ipynb.py in the upstream project (there
a jupytext wrapper run as a pre-commit hook). This standalone version has
no dependencies: it splits a MyST markdown file on ````{code-cell}``
fences, emitting markdown cells for prose and code cells for fenced
blocks, and writes nbformat-4 JSON next to the source (or to ``--out``).

Usage::

    python scripts/myst_to_ipynb.py docs/tutorials/*.md [--out-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import re
from pathlib import Path

_FENCE = re.compile(r"^```\{code-cell\}[^\n]*\n(.*?)^```\s*$", re.M | re.S)
_FRONTMATTER = re.compile(r"\A---\n.*?\n---\n", re.S)
_CELL_OPTION = re.compile(r"^:([\w-]+):\s*(.*)$")


def _strip_options(code: str):
    """Split leading MyST ``:key: value`` option lines from cell code."""
    lines = code.split("\n")
    options = {}
    while lines:
        m = _CELL_OPTION.match(lines[0])
        if m:
            options[m.group(1)] = m.group(2)
            lines.pop(0)
        elif not lines[0].strip() and options:
            lines.pop(0)  # blank separator after the option block
            break
        else:
            break
    return "\n".join(lines), options


def split_cells(text: str):
    """Yield ("markdown"|"code", source) pairs for a MyST document.

    Code sources have MyST cell options (``:tags: [...]`` etc.) stripped,
    so they are directly executable.
    """
    text = _FRONTMATTER.sub("", text)
    pos = 0
    for m in _FENCE.finditer(text):
        prose = text[pos : m.start()].strip("\n")
        if prose.strip():
            yield "markdown", prose
        code, _ = _strip_options(m.group(1).rstrip("\n"))
        yield "code", code
        pos = m.end()
    tail = text[pos:].strip("\n")
    if tail.strip():
        yield "markdown", tail


def to_notebook(text: str) -> dict:
    cells = []
    for i, (kind, source) in enumerate(split_cells(text)):
        lines = [line + "\n" for line in source.split("\n")]
        if lines:
            lines[-1] = lines[-1].rstrip("\n")
        # nbformat >= 4.5 requires a unique per-cell id
        cell = {"cell_type": kind, "id": f"cell-{i}", "metadata": {}, "source": lines}
        if kind == "code":
            cell.update(execution_count=None, outputs=[])
        cells.append(cell)
    return {
        "cells": cells,
        "metadata": {
            "kernelspec": {
                "display_name": "Python 3",
                "language": "python",
                "name": "python3",
            },
            "language_info": {"name": "python"},
        },
        "nbformat": 4,
        "nbformat_minor": 5,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("files", nargs="+", type=Path)
    parser.add_argument("--out-dir", type=Path, default=None)
    args = parser.parse_args(argv)
    for src in args.files:
        nb = to_notebook(src.read_text(encoding="utf-8"))
        out_dir = args.out_dir or src.parent
        out_dir.mkdir(parents=True, exist_ok=True)
        out = out_dir / (src.stem + ".ipynb")
        out.write_text(json.dumps(nb, indent=1), encoding="utf-8")
        print(f"{src} -> {out} ({len(nb['cells'])} cells)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
