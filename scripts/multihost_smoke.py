"""Multi-host bring-up smoke: a real ``jax.distributed`` training run.

Launched by ``tests/integration/test_multihost.py`` and the
``multihost_dp_fsdp`` leg of ``__graft_entry__.dryrun_multichip`` as a
pair of OS processes (CPU backend, ``--xla_force_host_platform_device_
count`` local devices each, Gloo cross-process collectives) — the same
control plane ``jax.distributed`` uses on TPU pods, minus the hardware.

Each process feeds ONLY its own batch rows (``local_batches`` →
``DeviceFeed`` assembling global arrays from process-local shards), runs
a dp×fsdp ``compile_step`` training loop, and prints the final loss plus
a replicated parameter checksum. The single-process invocation
(``--num-processes 1``) is the equality reference: same seeds, same
global batch, same step count — the distributed run must land on the
same numbers.

Reference anchor: the reference proves its control plane by running
through a real (sandboxed) Flyte deployment
(tests/integration/test_flyte_remote.py:33-57); this is the TPU-native
equivalent with a real distributed runtime.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path
from typing import Dict, Optional

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))


def _worker_env() -> Dict[str, str]:
    # the worker sets its own device count; a parent's XLA_FLAGS (e.g.
    # the test conftest's 8-device flag) must not leak in ahead of it
    return {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}


def launch_single(
    *, local_devices: int, steps: int = 6, timeout: int = 300
) -> dict:
    """Run the single-process reference and return its result JSON."""
    import subprocess

    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--local-devices", str(local_devices), "--steps", str(steps)],
        capture_output=True, text=True, timeout=timeout, env=_worker_env(),
    )
    if out.returncode != 0:
        raise RuntimeError(f"single-process worker failed: {out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def launch_pair(
    *,
    local_devices: int,
    steps: int = 6,
    timeout: int = 300,
    port: Optional[int] = None,
) -> dict:
    """Run the 2-process ``jax.distributed`` pair; return process 0's
    result JSON. On timeout both workers are killed and their stderr
    tails surface in the raised error (a hung Gloo bring-up otherwise
    leaks two live processes and all diagnostics)."""
    import socket
    import subprocess

    if port is None:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--process-id", str(pid), "--num-processes", "2",
             "--coordinator", f"127.0.0.1:{port}",
             "--local-devices", str(local_devices), "--steps", str(steps)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=_worker_env(),
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=timeout))
    except subprocess.TimeoutExpired:
        tails = []
        for p in procs:
            if p.poll() is None:
                p.kill()
            stdout, stderr = p.communicate()
            tails.append(stderr[-1000:] if stderr else "")
        raise RuntimeError(
            f"multihost pair timed out after {timeout}s; worker stderr "
            f"tails: {tails}"
        )
    for p, (stdout, stderr) in zip(procs, outs):
        if p.returncode != 0:
            raise RuntimeError(
                f"multihost worker rc={p.returncode}: {stderr[-2000:]}"
            )
    return json.loads(outs[0][0].strip().splitlines()[-1])


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--process-id", type=int, default=0)
    ap.add_argument("--num-processes", type=int, default=1)
    ap.add_argument("--coordinator", default="127.0.0.1:12321")
    ap.add_argument("--local-devices", type=int, default=8)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--global-batch", type=int, default=64)
    args = ap.parse_args()

    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={args.local_devices}"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

    if args.num_processes > 1:
        from unionml_tpu.parallel import multihost_initialize

        assert multihost_initialize(
            args.coordinator, args.num_processes, args.process_id
        ), "jax.distributed bring-up failed"
        assert jax.process_count() == args.num_processes

    import jax.numpy as jnp
    import numpy as np

    from unionml_tpu.data import local_batches, prefetch_to_device
    from unionml_tpu.parallel import ShardingConfig, compile_step

    total = args.num_processes * args.local_devices
    cfg = ShardingConfig(data=2, fsdp=total // 2)

    dim = 16
    true_w = np.linspace(-1.0, 1.0, dim).astype(np.float32)

    def global_batch(step: int):
        # every host derives the same global batch from the step seed;
        # local_batches then keeps only this process's rows
        rng = np.random.default_rng(1000 + step)
        x = rng.normal(size=(args.global_batch, dim)).astype(np.float32)
        y = x @ true_w + 0.25
        return x, y

    def step_fn(state, batch):
        x, y = batch

        def loss_fn(w, b):
            pred = x @ w + b
            return jnp.mean((pred - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
            state["w"], state["b"]
        )
        return (
            {"w": state["w"] - 0.1 * grads[0], "b": state["b"] - 0.1 * grads[1]},
            {"loss": loss},
        )

    state = {"w": jnp.zeros((dim,)), "b": jnp.zeros(())}
    compiled, state = compile_step(step_fn, state, sharding=cfg, donate_state=False)

    batches = (global_batch(s) for s in range(args.steps))
    if jax.process_count() > 1:
        batches = local_batches(batches, cfg, args.global_batch)
    metrics = {"loss": jnp.zeros(())}
    for batch in prefetch_to_device(batches, sharding=cfg):
        state, metrics = compiled(state, batch)

    from jax.sharding import NamedSharding, PartitionSpec

    checksum = jax.jit(
        lambda s: jnp.sum(s["w"] ** 2) + s["b"] ** 2,
        out_shardings=NamedSharding(cfg.mesh(), PartitionSpec()),
    )(state)
    if jax.process_index() == 0:
        print(json.dumps({
            "processes": jax.process_count(),
            "devices": len(jax.devices()),
            "steps": args.steps,
            "loss": float(metrics["loss"]),
            "checksum": float(checksum),
        }))


if __name__ == "__main__":
    main()
