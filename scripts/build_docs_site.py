"""Build the static documentation site from docs/*.md.

The reference ships a Sphinx site (reference: docs/source/conf.py +
14 .md/.rst sources with nav). This repo's docs are plain markdown kept
current by tests (test_docs_reference.py, test_tutorials.py); this
script renders them into a browsable site with a navigation sidebar
using only the stdlib + the `markdown` package (no Sphinx/mkdocs in the
image — `mkdocs.yml` at the repo root carries the same nav for
environments that have mkdocs installed).

Usage::

    python scripts/build_docs_site.py [--out site] [--check]

``--check`` exits non-zero if any nav entry is missing or any internal
.md link would 404 in the rendered site (CI runs this).
"""

from __future__ import annotations

import argparse
import re
import shutil
import sys
from pathlib import Path

import markdown

ROOT = Path(__file__).resolve().parents[1]
DOCS = ROOT / "docs"

# nav order (mirrored in mkdocs.yml — keep in sync)
NAV = [
    ("Overview", "index.md"),
    ("Quickstart", "quickstart.md"),
    ("Dataset", "dataset.md"),
    ("Model", "model.md"),
    ("Parallelism", "parallelism.md"),
    ("Serving", "serving.md"),
    ("Prefix caching", "prefix_caching.md"),
    ("Observability", "observability.md"),
    ("Checkpoints", "checkpoints.md"),
    ("Remote deployment", "remote.md"),
    ("Reliability", "reliability.md"),
    ("Serving robustness", "robustness.md"),
    ("Performance", "performance.md"),
    ("CLI", "cli.md"),
    ("Tutorial: MNIST", "tutorials/mnist.md"),
    ("Tutorial: Vision", "tutorials/vision.md"),
    ("Tutorial: LLM serving", "tutorials/llm_serving.md"),
    ("Tutorial: Checkpoints", "tutorials/checkpoints.md"),
    ("API reference", "api_reference.md"),
    ("CLI reference", "cli_reference.md"),
]

TEMPLATE = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>{title} — unionml-tpu</title>
<style>
body {{ margin: 0; font: 16px/1.6 system-ui, sans-serif; color: #1a1a2e; }}
.wrap {{ display: flex; min-height: 100vh; }}
nav {{ width: 240px; flex: none; background: #f4f4f8; padding: 1.5rem 1rem;
      border-right: 1px solid #e0e0e8; }}
nav h1 {{ font-size: 1.1rem; margin: 0 0 1rem; }}
nav a {{ display: block; padding: .25rem .5rem; color: #333; border-radius: 4px;
        text-decoration: none; }}
nav a:hover {{ background: #e8e8f0; }}
nav a.active {{ background: #dcdcf0; font-weight: 600; }}
main {{ flex: 1; max-width: 860px; padding: 2rem 3rem; overflow-x: auto; }}
pre {{ background: #f6f8fa; padding: .8rem 1rem; border-radius: 6px;
      overflow-x: auto; font-size: .9rem; }}
code {{ background: #f6f8fa; padding: .1rem .3rem; border-radius: 3px;
       font-size: .92em; }}
pre code {{ padding: 0; background: none; }}
table {{ border-collapse: collapse; margin: 1rem 0; }}
th, td {{ border: 1px solid #d8d8e0; padding: .4rem .7rem; text-align: left; }}
th {{ background: #f4f4f8; }}
h1, h2, h3 {{ scroll-margin-top: 1rem; }}
a {{ color: #3146b0; }}
</style>
</head>
<body>
<div class="wrap">
<nav>
<h1>unionml-tpu</h1>
{nav}
</nav>
<main>
{body}
</main>
</div>
</body>
</html>
"""


def out_path(md_rel: str) -> str:
    return md_rel[:-3] + ".html"


def render_nav(current: str) -> str:
    depth = current.count("/")
    prefix = "../" * depth
    items = []
    for title, page in NAV:
        cls = ' class="active"' if page == current else ""
        items.append(f'<a href="{prefix}{out_path(page)}"{cls}>{title}</a>')
    return "\n".join(items)


def rewrite_links(html: str, current: str, known: set) -> list:
    """Point internal .md links at their rendered .html; report breaks."""
    broken = []

    def sub(m):
        href = m.group(1)
        if href.startswith(("http://", "https://", "#", "mailto:")):
            return m.group(0)
        target, _, frag = href.partition("#")
        if not target.endswith(".md"):
            return m.group(0)
        resolved = (Path(current).parent / target).as_posix()
        resolved = re.sub(r"(^|/)\./", r"\1", resolved)
        while True:  # normalize a/../b; a LEADING ../ escapes docs/ → broken
            collapsed = re.sub(r"[^/.][^/]*/\.\./", "", resolved, count=1)
            if collapsed == resolved:
                break
            resolved = collapsed
        if resolved.startswith("../") or resolved not in known:
            broken.append((current, href))
            return m.group(0)  # leaves the .md href; reported as broken
        new = out_path(target) + (f"#{frag}" if frag else "")
        return f'href="{new}"'

    return re.sub(r'href="([^"]+)"', sub, html), broken


def build(out_dir: Path, check: bool) -> int:
    known = {page for _, page in NAV}
    missing = [page for page in known if not (DOCS / page).exists()]
    if missing:
        print(f"nav entries missing from docs/: {sorted(missing)}")
        return 1
    if not check:
        shutil.rmtree(out_dir, ignore_errors=True)
        out_dir.mkdir(parents=True, exist_ok=True)
    md = markdown.Markdown(extensions=["fenced_code", "tables", "toc"])
    all_broken = []
    for title, page in NAV:
        src = (DOCS / page).read_text(encoding="utf-8")
        body = md.reset().convert(src)
        body, broken = rewrite_links(body, page, known)
        all_broken.extend(broken)
        html = TEMPLATE.format(title=title, nav=render_nav(page), body=body)
        if not check:
            dest = out_dir / out_path(page)
            dest.parent.mkdir(parents=True, exist_ok=True)
            dest.write_text(html, encoding="utf-8")
    if all_broken:
        for page, href in all_broken:
            print(f"broken internal link in {page}: {href}")
        return 1
    if not check:
        print(f"site built: {out_dir} ({len(NAV)} pages)")
    else:
        print(f"docs site check OK ({len(NAV)} pages, links resolve)")
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default=str(ROOT / "site"))
    parser.add_argument("--check", action="store_true")
    args = parser.parse_args()
    return build(Path(args.out), args.check)


if __name__ == "__main__":
    sys.exit(main())
