"""Multi-host TENSOR-PARALLEL SERVING smoke: jax.distributed inference.

Training has a true 2-process validation (``multihost_smoke.py``);
this is the serving counterpart: a pair of OS processes (CPU backend,
Gloo collectives — the same control plane as TPU pods) hold a Llama
whose parameters are tensor-sharded ACROSS the processes, and serve it
through :func:`unionml_tpu.models.generate.make_lm_predictor` with
host 0 fronting HTTP:

- host 0 runs a :class:`~unionml_tpu.serving.http.ServingApp`; each
  request's prompt is broadcast to every host
  (``multihost_utils.broadcast_one_to_all`` — the standard multi-host
  serving pattern: all controllers must enter the jitted computation in
  lockstep), then every host runs the SAME sharded generate;
- the single-process invocation (``--num-processes 1``) is the equality
  reference: the pair's HTTP response must be token-identical.

Launched by ``__graft_entry__.dryrun_multichip`` (leg 9) and
``tests/integration/test_multihost.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path
from typing import Dict, Optional

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

PROMPT = [7, 3, 9, 2, 11, 5]
MAX_NEW = 6


def _worker_env() -> Dict[str, str]:
    return {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}


def launch_single(*, local_devices: int, timeout: int = 300) -> dict:
    import subprocess

    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--local-devices", str(local_devices)],
        capture_output=True, text=True, timeout=timeout, env=_worker_env(),
    )
    if out.returncode != 0:
        raise RuntimeError(f"single-process worker failed: {out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def launch_pair(
    *, local_devices: int, timeout: int = 300, port: Optional[int] = None
) -> dict:
    import socket
    import subprocess

    if port is None:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--process-id", str(pid), "--num-processes", "2",
             "--coordinator", f"127.0.0.1:{port}",
             "--local-devices", str(local_devices)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=_worker_env(),
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=timeout))
    except subprocess.TimeoutExpired:
        tails = []
        for p in procs:
            if p.poll() is None:
                p.kill()
            stdout, stderr = p.communicate()
            tails.append(stderr[-1000:] if stderr else "")
        raise RuntimeError(
            f"multihost serving pair timed out after {timeout}s; worker "
            f"stderr tails: {tails}"
        )
    for p, (stdout, stderr) in zip(procs, outs):
        if p.returncode != 0:
            raise RuntimeError(
                f"multihost serving worker rc={p.returncode}: {stderr[-2000:]}"
            )
    return json.loads(outs[0][0].strip().splitlines()[-1])


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--process-id", type=int, default=0)
    ap.add_argument("--num-processes", type=int, default=1)
    ap.add_argument("--coordinator", default="127.0.0.1:12321")
    ap.add_argument("--local-devices", type=int, default=8)
    args = ap.parse_args()

    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={args.local_devices}"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

    if args.num_processes > 1:
        from unionml_tpu.parallel import multihost_initialize

        assert multihost_initialize(
            args.coordinator, args.num_processes, args.process_id
        ), "jax.distributed bring-up failed"
        assert jax.process_count() == args.num_processes

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding

    from unionml_tpu.models import (
        LLAMA_PARTITION_RULES,
        Llama,
        LlamaConfig,
        make_lm_predictor,
    )
    from unionml_tpu.parallel import ShardingConfig

    total = args.num_processes * args.local_devices
    cfg = LlamaConfig.tiny(vocab_size=128)
    module = Llama(cfg)
    # every process derives the IDENTICAL full tree from the same seed,
    # then assembles the cross-process tensor-sharded global arrays from
    # its local copy (the standard way to materialize a sharded tree
    # without a host ever holding someone else's shard exclusively)
    host_params = jax.jit(module.init)(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    sc = ShardingConfig(
        data=1, tensor=total, rules=LLAMA_PARTITION_RULES,
        devices=jax.devices(),
    )
    mesh = sc.mesh()

    from jax.tree_util import tree_map_with_path

    def _path_str(path) -> str:
        parts = []
        for p in path:
            key = getattr(p, "key", getattr(p, "name", getattr(p, "idx", p)))
            parts.append(str(key))
        return "/".join(parts)

    def to_global(path, leaf):
        local = np.asarray(leaf)
        spec = sc.param_pspec(_path_str(path), leaf)
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(
            local.shape, sharding, lambda idx: local[idx]
        )

    params = tree_map_with_path(to_global, host_params)
    predictor = make_lm_predictor(
        module, max_new_tokens=MAX_NEW, bucket_lens=(16,),
        max_len=16 + MAX_NEW,
    )

    if args.num_processes == 1:
        tokens = predictor(params, [PROMPT])[0]
        print(json.dumps({
            "processes": 1, "devices": len(jax.devices()), "tokens": tokens,
        }))
        return

    from jax.experimental import multihost_utils

    plen = len(PROMPT)
    if args.process_id == 0:
        # host 0 fronts HTTP; its predictor body broadcasts each prompt
        # so every host enters the sharded generate in lockstep
        import urllib.request

        from unionml_tpu import Dataset, Model
        from unionml_tpu.model import ModelArtifact
        from unionml_tpu.serving.http import ServingApp

        dataset = Dataset(name="mh_serve_data", targets=[])

        @dataset.reader
        def reader() -> list:
            return []

        model = Model(name="mh_serve", init=lambda: {}, dataset=dataset)

        @model.trainer
        def trainer(obj: dict, features: list) -> dict:
            return obj

        @model.predictor
        def serve_predict(obj: dict, prompts: list) -> list:
            row = np.asarray(prompts[0], np.int32)
            multihost_utils.broadcast_one_to_all(row)
            return predictor(params, [row.tolist()])

        model.artifact = ModelArtifact({})
        app = ServingApp(model, batch=False)
        host, port = app.serve(host="127.0.0.1", port=0, blocking=False)
        body = json.dumps({"features": [PROMPT]}).encode()
        req = urllib.request.Request(
            f"http://{host}:{port}/predict", data=body,
            headers={"Content-Type": "application/json"},
        )
        resp = json.loads(urllib.request.urlopen(req, timeout=240).read())
        tokens = resp["predictions"][0] if isinstance(resp, dict) else resp[0]
        app.shutdown()
        print(json.dumps({
            "processes": jax.process_count(),
            "devices": len(jax.devices()),
            "tokens": tokens,
            "via": "http",
        }))
    else:
        # worker host: receive the broadcast prompt, join the generate
        row = multihost_utils.broadcast_one_to_all(
            np.zeros((plen,), np.int32)
        )
        predictor(params, [np.asarray(row).tolist()])


if __name__ == "__main__":
    main()
